"""Prepared-query cache: optimized plan + physical plan + pin-bytes estimate
keyed by logical-plan STRUCTURE, reusing the residency manager's
literal-compare contract (PR 2).

RDBMS prepared-statement shape applied to the engine: a serving session's
repeat query skips the optimizer and the physical translation entirely and
executes the cached physical plan, whose device stages then land on the warm
HBM planes (residency rebinds by content, the decision caches hold the
cost-model verdicts for the same structural keys, and the jit compile cache
holds the stage programs) — the repeat path is admission + dispatch + d2h.

Key contract (mirrors device/residency.py expr_structure): the cache key is
the plan SKELETON — node types, masked expressions, source-table identity
tokens — with the literal values stored in the entry and compared ON LOOKUP.
Two fingerprint-equal plans differing only in predicate literals therefore
NEVER share a prepared entry: the literal mismatch replans and replaces the
slot (one slot per query shape, like the residency cache), so a varying-
literal stream is bounded while a stale-literal plan can never be served.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, List, Optional, Tuple

from ..device.residency import expr_structure, identity_token
from ..expressions import Expression
from ..observability.metrics import registry

# prepared entries kept per cache (LRU on lookup order): serving sessions see
# a bounded set of query shapes; past the cap the coldest shape replans
DEFAULT_PREPARED_CAP = 64


def plan_structure(plan) -> Tuple[tuple, tuple]:
    """(skeleton, literals) for one LOGICAL plan.

    The skeleton walks the plan preorder; each node contributes its type
    name plus every public field, with expressions masked to their literal-
    free skeletons (literals collected separately, in walk order), child
    plans reduced to arity markers (the preorder walk carries the shape),
    in-memory partitions reduced to identity tokens (device/residency.py —
    monotonic, never reused, so a new table can never alias a dead one), and
    unknown objects (scan operators, UDF handles) likewise identity-keyed.
    Two queries over the same resident tables differing only in literal
    values share one skeleton — the prepared cache compares their literals
    on lookup."""
    skel: List[tuple] = []
    lits: List[tuple] = []
    for node in plan.walk():
        row: List[Any] = [type(node).__name__]
        fields = vars(node)
        for name in sorted(fields):
            if name.startswith("_"):
                continue
            row.append(name)
            row.append(_field_key(fields[name], lits))
        skel.append(tuple(row))
    return tuple(skel), tuple(lits)


def _field_key(val, lits: List[tuple]):
    from ..plan.logical import LogicalPlan

    if isinstance(val, LogicalPlan):
        return "<child>"  # subtree shape arrives via the preorder walk
    if isinstance(val, Expression):
        s, l = expr_structure(val)
        lits.extend(l)
        return ("expr", s)
    if isinstance(val, (list, tuple)):
        return tuple(_field_key(v, lits) for v in val)
    if isinstance(val, (str, int, float, bool, bytes, type(None))):
        return ("p", val)
    if isinstance(val, dict):
        return tuple((k, _field_key(v, lits)) for k, v in sorted(val.items()))
    # data partitions, scan operators, UDF handles: identity-keyed — same
    # object => same slot; a rebuilt source replans (safe default)
    return ("id", type(val).__name__, identity_token(val))


def estimate_pin_bytes(physical) -> int:
    """Pin-scope budget estimate for one physical plan: the device bytes its
    execution is expected to pin, fed to the HBM admission controller
    (ResidencyManager.admit). Primary source: the cost model's device-bytes
    probes as exposed through the plan fingerprint (distributed/affinity.py —
    per-slot byte estimates for every residency slot the device stages would
    touch). Fallback for device nodes whose columns carry no content
    fingerprint (and for the join stages, whose identity-dependent slots are
    deliberately absent from fingerprints): the in-memory input bytes under
    each device node, a coarse upper bound. Host-only plans estimate 0 and
    admit immediately."""
    from ..plan import physical as pp

    try:
        from ..distributed.affinity import plan_fingerprint

        fp = plan_fingerprint(physical)
    except Exception:  # lint: ignore[broad-except] -- estimate is advisory
        fp = ()
    total = sum(est for _k, est in fp)
    if total:
        return total
    device_types = (pp.DeviceGroupedAgg, pp.DeviceFilterAgg,
                    pp.DeviceJoinAgg, pp.DeviceJoinTopN)
    try:
        for node in physical.walk():
            if isinstance(node, device_types):
                for scan in (n for n in node.walk()
                             if isinstance(n, pp.InMemoryScan)):
                    for part in scan.partitions:
                        for b in part.batches:
                            total += b.size_bytes()
    except Exception:  # lint: ignore[broad-except] -- estimate is advisory
        return total
    return total


class PreparedEntry:
    __slots__ = ("literals", "builder", "physical", "est_pin_bytes",
                 "fingerprint", "hits", "plan_seconds", "observed_pin_bytes",
                 "_est_upper_bytes")

    def __init__(self, literals, builder, physical, est_pin_bytes: int,
                 fingerprint, plan_seconds: float):
        self.literals = literals
        self.builder = builder          # optimized LogicalPlanBuilder (_preoptimized)
        self.physical = physical        # cached physical plan (in-process path only)
        self.est_pin_bytes = est_pin_bytes
        self.fingerprint = fingerprint  # (stable_slot_key, est_bytes) pairs
        self.hits = 0
        self.plan_seconds = plan_seconds
        # admission calibration: max pin-scope byte high-water OBSERVED across
        # this entry's executions (None until the first completed run), and
        # the original fingerprint-derived upper bound the calibrated
        # estimate can recover toward if a later repeat observes more
        self.observed_pin_bytes = None
        self._est_upper_bytes = est_pin_bytes

    def note_observed_pin(self, observed: int) -> None:
        """Calibrate the reservation toward the observed pin-scope
        high-water: ``est = min(fingerprint upper bound, max observed)``.
        Warm repeats reserve what repeats actually pin, admission packs
        tighter, ``hbm_reserved_bytes`` drops — and because the observation
        floor is the max seen so far, a cold run's PARTIAL working set (a
        mid-query fallback) can't permanently under-reserve: a later repeat
        observing more raises the estimate back toward the upper bound. The
        estimate stays advisory: the pin scope still degrades safely if a
        run pins more than reserved. A ZERO observation is discarded — a run
        that pinned nothing executed on the host path and says nothing about
        the device working set a later repeat would reserve for."""
        observed = int(observed)
        if observed <= 0:
            return
        prev = self.observed_pin_bytes
        self.observed_pin_bytes = observed if prev is None else max(prev, observed)
        new_est = min(self._est_upper_bytes, self.observed_pin_bytes)
        if new_est < self.est_pin_bytes:
            registry().inc("serve_pin_calibrations")
        self.est_pin_bytes = new_est


class PreparedQueryCache:
    """Thread-safe bounded cache of prepared queries, one slot per plan
    skeleton."""

    def __init__(self, cap: int = DEFAULT_PREPARED_CAP):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, PreparedEntry]" = OrderedDict()
        self.cap = cap

    def get_or_plan(self, builder,
                    keep_physical: bool = True) -> Tuple[PreparedEntry, bool]:
        """Return (entry, hit). A hit requires the skeleton to match AND the
        stored literals to EQUAL the query's (the PR 2 literal-compare
        contract) AND the entry to carry what the caller executes (a cached
        physical plan for the in-process path; `keep_physical=False` callers
        — distributed runners, whose localize() pass mutates translated
        plans — reuse only the optimized logical plan and re-translate).
        A literal mismatch replans IN the same slot."""
        skel, lits = plan_structure(builder.plan)
        with self._lock:
            e = self._entries.get(skel)
            if (e is not None and e.literals == lits
                    and (e.physical is not None) == keep_physical):
                self._entries.move_to_end(skel)
                e.hits += 1
                registry().inc("serve_prepared_hits")
                return e, True
        import time

        from ..plan.physical import translate

        t0 = time.perf_counter()
        optimized = builder.optimize()
        # mark so a runner handed this builder skips re-optimizing
        optimized._preoptimized = True
        physical = translate(optimized.plan)
        est = estimate_pin_bytes(physical)
        try:
            from ..distributed.affinity import plan_fingerprint

            fp = plan_fingerprint(physical)
        except Exception:  # lint: ignore[broad-except] -- affinity fingerprint is advisory
            fp = ()
        e = PreparedEntry(lits, optimized,
                          physical if keep_physical else None,
                          est, fp, time.perf_counter() - t0)
        with self._lock:
            self._entries[skel] = e
            self._entries.move_to_end(skel)
            while len(self._entries) > self.cap:
                self._entries.popitem(last=False)
        registry().inc("serve_prepared_misses")
        return e, False

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
