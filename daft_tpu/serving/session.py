"""ServingSession: the driver-side concurrent query session.

Execution model: ``submit()`` enqueues a ticket into the fair admission queue
(per-tenant round-robin — admission.py) and returns a ``ServeFuture``;
``max_concurrent`` session worker threads pull tickets, prepare them through
the PreparedQueryCache (optimize+translate skipped on a repeat shape), pass
the HBM admission controller (``ResidencyManager.admit`` — a pin-scope byte
reservation that QUEUES over-budget queries instead of letting them thrash
each other's pinned planes out of HBM), and execute:

- in-process (runner=None, the default): the cached physical plan streams
  through the executor directly — the serving fast path. Device stages pin
  their working sets per executing thread (pin scopes are thread-local, so
  concurrent queries' scopes never interleave), the decision caches are
  locked, and the thread runs under span_scope(None) so a query being
  profiled elsewhere never receives this query's spans.
- through a runner (e.g. DistributedRunner): the prepared optimized plan is
  handed to the runner (re-optimization short-circuits); concurrent sub-plan
  streams interleave fairly across the shared worker pool (the pool's
  dispatcher deals tasks round-robin per stage stream).

Every query emits a ServeQueryRecord to subscribers (dashboard per-tenant
hit-rate table, tenant-labeled /metrics latency histogram, event log) and
bumps serve_queries_total / serve_prepared_hits / admission_waits_total;
serve_queue_depth tracks the admission queue.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, List, Optional

from ..cancellation import (QueryCancelled, raise_if_cancelled,
                            set_cancel_event)
from ..config import execution_config
from ..device.residency import manager as _residency
from ..observability import ServeQueryRecord, notify, subscribers_active
from ..observability.metrics import registry
from .admission import FairAdmissionQueue
from .prepared import PreparedQueryCache


class ServeFuture:
    """Result handle for one submitted query."""

    def __init__(self, query_id: str, tenant: str):
        self.query_id = query_id
        self.tenant = tenant
        self._done = threading.Event()
        self._parts: Optional[List[Any]] = None
        self._error: Optional[BaseException] = None
        # cancellation: the event is installed as the executing thread's
        # cancellation token (daft_tpu/cancellation.py) for the running case;
        # _queue/_ticket let cancel() pull a still-queued query out of the
        # admission queue before it ever starts
        self._cancel_ev = threading.Event()
        self._queue: Optional[FairAdmissionQueue] = None
        self._ticket: Optional["_Ticket"] = None
        # filled at resolution for caller-side attribution
        self.seconds = 0.0
        self.prepared_hit = False
        self.admission_wait_s = 0.0
        self.cancelled = False

    def result(self, timeout: Optional[float] = None) -> List[Any]:
        """The query's result MicroPartitions (raises what execution raised)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id} not done within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._parts  # type: ignore[return-value]

    def to_pydict(self, timeout: Optional[float] = None) -> dict:
        parts = self.result(timeout)
        out: dict = {}
        for p in parts:
            for k, v in p.to_pydict().items():
                out.setdefault(k, []).extend(v)
        return out

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> bool:
        """Cancel this query. A still-QUEUED query is removed from the
        admission queue, never reserves HBM, and resolves immediately with
        QueryCancelled. A RUNNING query is cancelled best-effort: its
        cancellation token trips the engine's cooperative checks (between
        distributed task stages — where the pool also drops the query's
        pending stream — inside the HBM admission wait, and between streamed
        result partitions in-process); a stage already on the workers
        completes and its results are discarded. Returns False only when the
        query already resolved (its result stands); True means the
        cancellation was delivered (for a queued query, guaranteed)."""
        if self._done.is_set():
            return False
        self._cancel_ev.set()
        q, t = self._queue, self._ticket
        if q is not None and t is not None and q.remove(t.tenant, t):
            registry().set_gauge("serve_queue_depth", float(q.depth()))
            registry().inc("serve_cancelled_total")
            self.cancelled = True
            self._reject(QueryCancelled(
                f"query {self.query_id} cancelled while queued"))
        return True

    def _resolve(self, parts: List[Any]) -> None:
        self._parts = parts
        self._done.set()

    def _reject(self, err: BaseException) -> None:
        self._error = err
        self._done.set()


class _Ticket:
    __slots__ = ("builder", "tenant", "future", "submitted")

    def __init__(self, builder, tenant: str, future: ServeFuture):
        self.builder = builder
        self.tenant = tenant
        self.future = future
        self.submitted = time.perf_counter()


class ServingSession:
    """N-concurrent-query session over the warm engine (see module doc).

    Args:
        max_concurrent: session worker threads (defaults to
            ExecutionConfig.max_concurrent_queries / DAFT_TPU_MAX_CONCURRENT_QUERIES).
        runner: execute through this Runner instead of the in-process
            executor (a DistributedRunner fans sub-plans across its pool;
            concurrent queries share it safely).
        prepared_cap: prepared-query cache slots (one per plan skeleton).
    """

    def __init__(self, max_concurrent: Optional[int] = None, runner=None,
                 prepared_cap: int = 64):
        cfg = execution_config()
        self.max_concurrent = (cfg.max_concurrent_queries
                               if max_concurrent is None else max_concurrent)
        if self.max_concurrent < 1:
            raise ValueError(
                f"max_concurrent must be >= 1, got {self.max_concurrent}")
        self._runner = runner
        self._queue = FairAdmissionQueue()
        self.prepared = PreparedQueryCache(prepared_cap)
        self._closed = threading.Event()
        self._stats_lock = threading.Lock()
        # tenant -> {"queries", "errors", "prepared_hits", "admission_waits",
        #            "wait_s", "seconds", "rows"}
        self._tenants: dict = {}
        self._threads = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"daft-serve-{i}")
            for i in range(self.max_concurrent)
        ]
        for t in self._threads:
            t.start()

    # ---- client API ----------------------------------------------------------------
    def submit(self, query, tenant: str = "default") -> ServeFuture:
        """Enqueue one query (a DataFrame or LogicalPlanBuilder) for `tenant`;
        returns a ServeFuture immediately."""
        if self._closed.is_set():
            raise RuntimeError("serving session is closed")
        builder = getattr(query, "_builder", query)
        fut = ServeFuture(uuid.uuid4().hex[:12], tenant)
        ticket = _Ticket(builder, tenant, fut)
        fut._queue = self._queue
        fut._ticket = ticket
        depth = self._queue.push(tenant, ticket)
        registry().set_gauge("serve_queue_depth", float(depth))
        if self._closed.is_set():
            # close() raced us: it may have drained the queue before our push
            # landed, leaving this ticket unserved forever — drain and reject
            # any stragglers (possibly including ours) so no client blocks
            self._drain_reject()
        return fut

    def run(self, query, tenant: str = "default",
            timeout: Optional[float] = None) -> List[Any]:
        """Synchronous convenience: submit + result."""
        return self.submit(query, tenant).result(timeout)

    def tenant_stats(self) -> dict:
        """Per-tenant serving totals (queries, prepared hits, admission
        waits, cumulative latency) — the dashboard's hit-rate table source."""
        with self._stats_lock:
            return {k: dict(v) for k, v in self._tenants.items()}

    def close(self, timeout: float = 10.0) -> None:
        """Drain nothing, stop accepting, join workers. Queued-but-unstarted
        tickets are rejected so no client blocks forever."""
        self._closed.set()
        for t in self._threads:
            t.join(timeout)
        self._drain_reject()

    def _drain_reject(self) -> None:
        while True:
            ticket = self._queue.pop(timeout=0)
            if ticket is None:
                break
            ticket.future._reject(RuntimeError("serving session closed"))
        registry().set_gauge("serve_queue_depth", 0.0)

    def __enter__(self) -> "ServingSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- worker side ---------------------------------------------------------------
    def _worker_loop(self) -> None:
        while not self._closed.is_set():
            ticket = self._queue.pop(timeout=0.1)
            if ticket is None:
                continue
            registry().set_gauge("serve_queue_depth", float(self._queue.depth()))
            self._execute(ticket)

    def _execute(self, ticket: _Ticket) -> None:
        from ..observability.placement import query_scope as _placement_scope
        from ..observability.runtime_stats import span_scope

        fut = ticket.future
        t0 = time.perf_counter()
        cfg = execution_config()
        entry = None
        err: Optional[str] = None
        rows = 0
        hit = False
        waited = False
        wait_s = 0.0
        exec_s = 0.0
        est = 0
        exc: Optional[BaseException] = None
        parts: List[Any] = []
        # this thread's cancellation token for the query's whole execution:
        # the engine's cooperative checks (distributed planner between
        # stages, pool run_tasks wait loop, HBM admission wait, and the
        # per-partition check below) all read it thread-locally
        set_cancel_event(fut._cancel_ev)
        try:
            # a cancel() that lost the queue-removal race (worker popped the
            # ticket first) still wins here, before planning or admission
            raise_if_cancelled(f"query {fut.query_id} cancelled")
            entry, hit = self.prepared.get_or_plan(
                ticket.builder, keep_physical=self._runner is None)
            est = entry.est_pin_bytes
            t_adm = time.perf_counter()
            # HBM admission: reserve this query's estimated pin-scope bytes;
            # waits (counted) while concurrently-admitted working sets have
            # the budget spoken for — never evicts a running query's pins
            with _residency().admit(est, tenant=ticket.tenant,
                                    tenant_budget=cfg.tenant_budget_bytes) as waited:
                wait_s = time.perf_counter() - t_adm
                t_exec = time.perf_counter()
                # span isolation: this thread's device spans stay out of any
                # globally-installed profiler recorder (cross-query bleed);
                # the placement scope isolates this query's decision records
                # the same way — concurrent tenants' placements never mix
                with span_scope(None), \
                        _placement_scope(tag=fut.query_id):
                    if self._runner is None:
                        from ..execution.executor import execute_plan

                        # observe the query's pin-scope HBM high-water so the
                        # prepared entry's reservation calibrates toward what
                        # repeats actually pin (admission packs tighter over
                        # time); pin scopes are thread-local, so concurrent
                        # queries' observations never mix
                        with _residency().observe_pins() as observed_pins:
                            # cooperative check between streamed partitions:
                            # the in-process path's natural yield points
                            for p in execute_plan(entry.physical):
                                raise_if_cancelled(
                                    f"query {fut.query_id} cancelled")
                                parts.append(p)
                        entry.note_observed_pin(observed_pins())
                    else:
                        parts = list(self._runner.run(entry.builder))
                exec_s = time.perf_counter() - t_exec
            rows = sum(p.num_rows for p in parts)
        except BaseException as e:  # noqa: BLE001 — the future carries it to the client
            err = f"{type(e).__name__}: {e}"
            exc = e
        finally:
            set_cancel_event(None)
        seconds = time.perf_counter() - t0
        # attribution BEFORE resolution: a client waking from result() must
        # see the final seconds/prepared_hit, not the defaults
        fut.seconds = seconds
        fut.prepared_hit = hit
        fut.admission_wait_s = wait_s
        if isinstance(exc, QueryCancelled):
            fut.cancelled = True
            registry().inc("serve_cancelled_total")
        if err is None:
            fut._resolve(parts)
        else:
            fut._reject(exc)
        registry().inc("serve_queries_total")
        with self._stats_lock:
            st = self._tenants.setdefault(ticket.tenant, {
                "queries": 0, "errors": 0, "prepared_hits": 0,
                "admission_waits": 0, "wait_s": 0.0, "seconds": 0.0,
                "rows": 0})
            st["queries"] += 1
            st["seconds"] += seconds
            st["rows"] += rows
            if hit:
                st["prepared_hits"] += 1
            if waited:
                st["admission_waits"] += 1
            st["wait_s"] += wait_s
            if err is not None:
                st["errors"] += 1
        if subscribers_active():
            notify("on_serve_query", ServeQueryRecord(
                query_id=fut.query_id, tenant=ticket.tenant, seconds=seconds,
                exec_seconds=exec_s, rows=rows, prepared_hit=hit,
                admission_wait_s=wait_s, est_pin_bytes=est, error=err,
                admission_waited=waited,
                in_process=self._runner is None))
        from ..observability import flight as _flight

        frec = _flight.recorder()
        if frec is not None:
            # tenant-tagged flight record: metrics stay OFF the record —
            # concurrent tenants share one process registry, so a per-query
            # delta here would bleed other tenants' counters into this
            # tenant's ring events (and their anomaly dumps)
            if waited:
                frec.record("admission", tenant=ticket.tenant,
                            query_id=fut.query_id,
                            wait_s=round(wait_s, 6), est_pin_bytes=est)
            if isinstance(exc, QueryCancelled):
                # a client-initiated cancel is not an engine anomaly: ring
                # record only, no query_error trigger
                frec.record("cancelled", tenant=ticket.tenant,
                            query_id=fut.query_id, seconds=round(seconds, 6))
            else:
                fp = str(getattr(entry, "fingerprint", "") or "")
                frec.note_query(_flight.plan_key(fp) if fp else "", seconds,
                                query_id=fut.query_id, tenant=ticket.tenant,
                                rows=rows, error=err)
