"""Serving tier: concurrent multi-query sessions over one warm engine.

The driver-side realization of the "warm residency as a product" ROADMAP
item: a ``ServingSession`` admits N concurrent queries through a fair
(per-tenant round-robin, FIFO within a tenant) admission queue, brackets each
execution with an HBM admission-controller reservation (queries queue when
the budget is spoken for instead of thrashing the residency LRU against each
other's pinned planes), and serves repeat queries through a prepared-query
cache that skips optimize+translate entirely and lands directly on the warm
HBM planes PRs 2-3 built.

    from daft_tpu.serving import ServingSession

    with ServingSession(max_concurrent=4) as sess:
        fut = sess.submit(df.groupby("k").agg(...), tenant="acme")
        parts = fut.result()          # list[MicroPartition]

Observability: serve_queue_depth / hbm_reserved_bytes gauges,
admission_waits_total / serve_prepared_hits / serve_prepared_misses /
serve_queries_total counters (Prometheus ``/metrics`` via the dashboard),
per-tenant latency histograms (tenant label on
daft_tpu_query_latency_seconds), and one ServeQueryRecord per query to
subscribers (dashboard per-tenant hit-rate table, event log schema v7).
"""

from ..cancellation import QueryCancelled
from .admission import (FairAdmissionQueue, TenantQueueFull, tenant_queue_cap,
                        tenant_weight)
from .prepared import PreparedQueryCache, estimate_pin_bytes, plan_structure
from .session import ServeFuture, ServingSession

__all__ = [
    "FairAdmissionQueue",
    "PreparedQueryCache",
    "QueryCancelled",
    "ServeFuture",
    "ServingSession",
    "TenantQueueFull",
    "estimate_pin_bytes",
    "plan_structure",
    "tenant_queue_cap",
    "tenant_weight",
]
