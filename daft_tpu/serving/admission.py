"""Fair admission queue: per-tenant round-robin with FIFO within a tenant.

The serving tier's first gate (the second is the HBM admission controller,
``device/residency.py ResidencyManager.admit``). Classic fair-queueing shape:
one FIFO per tenant, served round-robin, so a tenant replaying a 500-query
batch cannot starve an interactive tenant's single query — the interactive
query waits at most one rotation, not 500 slots. Tenants enter the rotation
on their first pending item and leave it when drained; the rotation pointer
survives drains so service order stays fair across bursts.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, List, Optional


class FairAdmissionQueue:
    """Thread-safe multi-tenant queue: ``push`` from any client thread,
    ``pop`` from the session's worker threads."""

    def __init__(self) -> None:
        self._cond = threading.Condition(threading.Lock())
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._rotation: List[str] = []
        self._pos = 0
        self._size = 0

    def push(self, tenant: str, item: Any) -> int:
        """Enqueue one item for `tenant`; returns the new total depth."""
        with self._cond:
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._rotation.append(tenant)
            q.append(item)
            self._size += 1
            self._cond.notify()
            return self._size

    def pop(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Dequeue the next item in per-tenant round-robin order (FIFO within
        the tenant), waiting up to `timeout` seconds; None on timeout."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._size > 0, timeout):
                return None
            n = len(self._rotation)
            for i in range(n):
                idx = (self._pos + i) % n
                tenant = self._rotation[idx]
                q = self._queues.get(tenant)
                if not q:
                    continue
                item = q.popleft()
                self._size -= 1
                if not q:
                    # drained: leave the rotation; the pointer lands on the
                    # tenant that was NEXT (now shifted into this slot)
                    self._rotation.pop(idx)
                    del self._queues[tenant]
                    self._pos = idx % max(len(self._rotation), 1)
                else:
                    self._pos = (idx + 1) % n
                return item
            return None  # unreachable while _size > 0

    def remove(self, tenant: str, item: Any) -> bool:
        """Remove one still-queued item (identity match) — the cancellation
        path: True only if the item was present, so exactly one of remove()
        and pop() ever owns a given ticket. Rotation fairness is preserved:
        removing a tenant's last item retires it from the rotation with the
        pointer re-aimed at whoever was next."""
        with self._cond:
            q = self._queues.get(tenant)
            if q is None:
                return False
            try:
                q.remove(item)
            except ValueError:
                return False
            self._size -= 1
            if not q:
                idx = self._rotation.index(tenant)
                self._rotation.pop(idx)
                del self._queues[tenant]
                if idx < self._pos:
                    self._pos -= 1
                self._pos = self._pos % max(len(self._rotation), 1)
            return True

    def depth(self) -> int:
        with self._cond:
            return self._size

    def tenants(self) -> List[str]:
        with self._cond:
            return list(self._rotation)
