"""Fair admission queue: per-tenant weighted round-robin, FIFO within a
tenant, with per-tenant queue-depth caps.

The serving tier's first gate (the second is the HBM admission controller,
``device/residency.py ResidencyManager.admit``). Classic fair-queueing shape:
one FIFO per tenant, served round-robin, so a tenant replaying a 500-query
batch cannot starve an interactive tenant's single query — the interactive
query waits at most one rotation, not 500 slots. Tenants enter the rotation
on their first pending item and leave it when drained; the rotation pointer
survives drains so service order stays fair across bursts.

QoS beyond fairness (the gateway's multi-tenant contract):

- **Weights** — ``DAFT_TPU_TENANT_WEIGHT_<TENANT>`` (tenant name uppercased,
  non-alphanumerics mapped to ``_``; default 1) gives a tenant up to that
  many services per rotation visit. A weight-3 tenant drains 3 queries each
  time the pointer reaches it while everyone else still gets their turn —
  proportional share, not priority (a weight can slow nobody to zero).
- **Queue-depth caps** — ``DAFT_TPU_TENANT_QUEUE_CAP`` (global default,
  0 = unbounded) with per-tenant override ``DAFT_TPU_TENANT_QUEUE_CAP_<TENANT>``.
  A push past the cap raises :class:`TenantQueueFull` instead of queuing
  unboundedly; the gateway answers it with a typed ``over_capacity`` wire
  error so a flooding client backs off at the front door rather than
  inflating everyone's rotation latency.

Knobs are resolved once per tenant per queue (first push/pop that sees the
tenant) so the hot path never re-reads the environment.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from ..observability.metrics import registry
from ..utils.env import env_int


class TenantQueueFull(RuntimeError):
    """A tenant's queue is at its depth cap; the submit was refused (the
    caller should surface a typed over-capacity error, not retry blindly)."""

    def __init__(self, tenant: str, cap: int, depth: int):
        self.tenant = tenant
        self.cap = cap
        self.depth = depth
        super().__init__(
            f"tenant {tenant!r} admission queue at cap ({depth}/{cap}); "
            f"retry later or raise DAFT_TPU_TENANT_QUEUE_CAP")


def _tenant_env_suffix(tenant: str) -> str:
    """Tenant name -> env-var suffix: uppercased, every non-alphanumeric
    mapped to '_' (so tenant 'client-3' reads the `..._CLIENT_3` knobs)."""
    return "".join(c if c.isalnum() else "_" for c in tenant.upper())


def tenant_weight(tenant: str) -> int:
    """DAFT_TPU_TENANT_WEIGHT_<TENANT>: services per rotation visit (>= 1)."""
    return env_int(f"DAFT_TPU_TENANT_WEIGHT_{_tenant_env_suffix(tenant)}",
                   1, lo=1)


def tenant_queue_cap(tenant: str) -> int:
    """Per-tenant queue-depth cap: DAFT_TPU_TENANT_QUEUE_CAP_<TENANT>,
    falling back to the global DAFT_TPU_TENANT_QUEUE_CAP (0 = unbounded)."""
    default = env_int("DAFT_TPU_TENANT_QUEUE_CAP", 0, lo=0)
    return env_int(f"DAFT_TPU_TENANT_QUEUE_CAP_{_tenant_env_suffix(tenant)}",
                   default, lo=0)


class FairAdmissionQueue:
    """Thread-safe multi-tenant queue: ``push`` from any client thread,
    ``pop`` from the session's worker threads."""

    def __init__(self) -> None:
        self._cond = threading.Condition(threading.Lock())
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._rotation: List[str] = []
        self._pos = 0
        self._size = 0
        # per-tenant QoS, resolved from the environment on first sight and
        # cached for the queue's lifetime (the hot path never re-reads env)
        self._weights: Dict[str, int] = {}
        self._caps: Dict[str, int] = {}
        # services the tenant AT the rotation pointer has left this visit
        # (weighted round-robin credit; reset whenever the pointer moves)
        self._credit = 0

    def _weight(self, tenant: str) -> int:
        w = self._weights.get(tenant)
        if w is None:
            w = self._weights[tenant] = tenant_weight(tenant)
        return w

    def _cap(self, tenant: str) -> int:
        c = self._caps.get(tenant)
        if c is None:
            c = self._caps[tenant] = tenant_queue_cap(tenant)
        return c

    def push(self, tenant: str, item: Any) -> int:
        """Enqueue one item for `tenant`; returns the new total depth.
        Raises :class:`TenantQueueFull` when the tenant is at its cap."""
        with self._cond:
            q = self._queues.get(tenant)
            cap = self._cap(tenant)
            if cap > 0 and q is not None and len(q) >= cap:
                registry().inc("serve_over_cap_rejections")
                raise TenantQueueFull(tenant, cap, len(q))
            if q is None:
                q = self._queues[tenant] = deque()
                self._rotation.append(tenant)
            q.append(item)
            self._size += 1
            self._cond.notify()
            return self._size

    def pop(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Dequeue the next item in weighted per-tenant round-robin order
        (FIFO within the tenant), waiting up to `timeout` seconds; None on
        timeout. A tenant with weight W is served up to W consecutive items
        each time the rotation pointer reaches it."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._size > 0, timeout):
                return None
            n = len(self._rotation)
            for i in range(n):
                idx = (self._pos + i) % n
                tenant = self._rotation[idx]
                q = self._queues.get(tenant)
                if not q:
                    continue
                if i > 0:
                    # pointer moved past drained/absent tenants: fresh visit
                    self._credit = 0
                if self._credit <= 0:
                    self._credit = self._weight(tenant)
                item = q.popleft()
                self._size -= 1
                self._credit -= 1
                if not q:
                    # drained: leave the rotation; the pointer lands on the
                    # tenant that was NEXT (now shifted into this slot)
                    self._rotation.pop(idx)
                    del self._queues[tenant]
                    self._pos = idx % max(len(self._rotation), 1)
                    self._credit = 0
                elif self._credit > 0:
                    # weighted visit continues: stay on this tenant
                    self._pos = idx
                else:
                    self._pos = (idx + 1) % n
                return item
            return None  # unreachable while _size > 0

    def remove(self, tenant: str, item: Any) -> bool:
        """Remove one still-queued item (identity match) — the cancellation
        path: True only if the item was present, so exactly one of remove()
        and pop() ever owns a given ticket. Rotation fairness is preserved:
        removing a tenant's last item retires it from the rotation with the
        pointer re-aimed at whoever was next."""
        with self._cond:
            q = self._queues.get(tenant)
            if q is None:
                return False
            try:
                q.remove(item)
            except ValueError:
                return False
            self._size -= 1
            if not q:
                idx = self._rotation.index(tenant)
                self._rotation.pop(idx)
                del self._queues[tenant]
                if idx < self._pos:
                    self._pos -= 1
                elif idx == self._pos:
                    self._credit = 0
                self._pos = self._pos % max(len(self._rotation), 1)
            return True

    def depth(self, tenant: Optional[str] = None) -> int:
        with self._cond:
            if tenant is None:
                return self._size
            q = self._queues.get(tenant)
            return len(q) if q else 0

    def tenants(self) -> List[str]:
        with self._cond:
            return list(self._rotation)
