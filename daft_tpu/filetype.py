"""Lazy File type: a file reference whose bytes are range-read on demand.

Reference parity: src/daft-file/src/file.rs (DaftFile: lazy handle + ranged
reads through the IO layer) and daft/file/file.py (the File python surface:
open/read/seek/tell/size/to_tempfile). A File value is just (url, io_config)
until opened; open() returns a seekable read-only file object that issues
RANGE requests through io/object_store.py — remote files never fully download
unless read() asks for everything.
"""

from __future__ import annotations

import io
import mimetypes
import os
from typing import Optional


class DaftFile(io.RawIOBase):
    """Seekable read-only file over an ObjectSource (local / s3 / gcs / http).

    Every read issues a ranged get for exactly the requested span, so random
    access into large remote objects stays cheap (reference: file.rs ranged
    reader)."""

    def __init__(self, url: str, io_config=None):
        super().__init__()
        from .io.object_store import resolve_source

        self._url = url
        self._source, self._path = resolve_source(url, io_config)
        self._pos = 0
        self._size: Optional[int] = None

    # ---- python file protocol ------------------------------------------------------
    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return True

    def tell(self) -> int:
        return self._pos

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            self._pos = offset
        elif whence == os.SEEK_CUR:
            self._pos += offset
        elif whence == os.SEEK_END:
            self._pos = self.size() + offset
        else:
            raise ValueError(f"invalid whence {whence}")
        if self._pos < 0:
            raise ValueError("negative seek position")
        return self._pos

    def size(self) -> int:
        if self._size is None:
            self._size = self._source.get_size(self._path)
        return self._size

    def read(self, n: int = -1) -> bytes:
        size = self.size()
        if self._pos >= size:
            return b""
        if n is None or n < 0:
            end = size
        else:
            end = min(self._pos + n, size)
        if end <= self._pos:
            return b""
        data = self._source.get(self._path, range=(self._pos, end))
        self._pos = end
        return data

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[:len(data)] = data
        return len(data)


class File:
    """Lazy file reference (reference: daft/file/file.py File). Carries only
    (url, io_config); bytes move when open()/read() ask for them."""

    __slots__ = ("_url", "_io_config")

    def __init__(self, url: str, io_config=None):
        self._url = url
        self._io_config = io_config

    def open(self) -> DaftFile:
        return DaftFile(self._url, self._io_config)

    @property
    def path(self) -> str:
        return self._url

    @property
    def name(self) -> str:
        return os.path.basename(self._url.rstrip("/"))

    def size(self) -> int:
        f = self.open()
        return f.size()

    def mime_type(self) -> str:
        guess, _ = mimetypes.guess_type(self._url)
        return guess or "application/octet-stream"

    def read(self, n: int = -1) -> bytes:
        with self.open() as f:
            return f.read(n)

    def to_tempfile(self):
        """Copy contents into a NamedTemporaryFile (for libraries that demand
        a real filesystem path)."""
        import shutil
        import tempfile

        tmp = tempfile.NamedTemporaryFile(suffix=os.path.splitext(self.name)[1])
        with self.open() as src:
            shutil.copyfileobj(src, tmp)
        tmp.flush()
        tmp.seek(0)
        return tmp

    def __repr__(self) -> str:
        return f"File({self._url!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, File) and other._url == self._url

    def __hash__(self) -> int:
        return hash(self._url)
