"""HostMemoryManager: a process-wide byte ledger for out-of-core execution.

Mirrors the HBM ResidencyManager's design (device/residency.py) on the host
side: ONE authority that knows how many bytes the engine's memory-hungry
sites currently hold, with a budget resolved from config
(``DAFT_TPU_MEMORY_LIMIT``), per-operator admission handles, pressure
callbacks, and ``host_bytes_tracked`` / ``host_bytes_high_water`` gauges in
the process metrics registry so per-query deltas land in QueryEnd.metrics,
EXPLAIN ANALYZE, the Prometheus exposition, and bench JSON.

Budget semantics (config.memory_limit_bytes):

- positive: that many bytes, shared by EVERY admitting site in the process —
  concurrent serving queries draw down one ledger instead of each believing
  it owns the whole budget;
- 0 (default): unbounded AND untracked — the zero-overhead contract: an
  unbudgeted query allocates no manager state, writes no gauges, and its
  operators run the plain in-memory paths;
- negative: auto — ``DAFT_TPU_MEMORY_FRACTION`` (default 0.6) of system RAM,
  probed once per process, the out-of-core mirror of the HBM auto budget.

Admission model: a blocking operator (agg/sort/join build/window) takes an
``operator_budget()`` handle and admits each buffered batch's bytes; once the
LEDGER crosses the budget the handle answers False and the operator switches
to its spilling strategy (daft_tpu/memory/spill.py), releasing its tracked
bytes as the buffers flush to disk. Streaming scans don't admit (they hold
one bounded window) but consult ``under_pressure()`` /
``wait_for_headroom()`` so a fast producer stalls — boundedly, never as a
correctness gate — while a downstream operator is at the wall.

Pressure: tracked >= ``DAFT_TPU_MEMORY_PRESSURE`` (default 0.8) of the
budget. ``on_pressure`` callbacks fire on each upward crossing (coarse
events only — one per crossing, never per batch admitted below the line).
All waits are bounded: the ledger drains when operators spill, and a
stalled producer resumes after ``max_wait`` even if it doesn't, so a
mis-sized budget degrades to throughput loss, not deadlock.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Callable, List, Optional

from ..observability.metrics import registry

# bounded pacing wait: long enough that a spilling operator usually drains
# the ledger first, short enough that a stuck ledger costs throughput only
_DEFAULT_MAX_WAIT_S = 0.25


class HostMemoryManager:
    """The process-wide host byte ledger (one per driver / worker process)."""

    def __init__(self) -> None:
        self._cond = threading.Condition(threading.Lock())
        self._tracked = 0
        self._high_water = 0
        self._auto_limit: Optional[int] = None
        self._pressure_cbs: List[Callable[[int, int], None]] = []
        self._in_pressure = False
        self._scopes: List["QueryMemoryScope"] = []

    # ---- budget resolution ---------------------------------------------------------
    def limit_bytes(self) -> int:
        """Effective host budget in bytes (0 = unbounded/untracked)."""
        from ..config import execution_config

        b = execution_config().memory_limit_bytes
        if b > 0:
            return b
        if b == 0:
            return 0
        if self._auto_limit is None:
            self._auto_limit = self._probe_auto_limit()
        return self._auto_limit

    def _probe_auto_limit(self) -> int:
        from ..config import execution_config

        ram = system_ram_bytes()
        if ram <= 0:
            return 0  # unprobeable platform: degrade to unbounded, loudly-documented
        return int(ram * execution_config().memory_fraction)

    # ---- ledger --------------------------------------------------------------------
    def track(self, nbytes: int) -> None:
        """Admit `nbytes` into the ledger (coarse events: one call per
        buffered batch / materialized scan task, never per row)."""
        if nbytes <= 0:
            return
        fire = None
        crossed = False
        with self._cond:
            self._tracked += nbytes
            if self._tracked > self._high_water:
                self._high_water = self._tracked
            for s in self._scopes:
                if self._tracked > s._peak:
                    s._peak = self._tracked
            registry().set_gauge("host_bytes_tracked", float(self._tracked))
            registry().set_gauge("host_bytes_high_water", float(self._high_water))
            # crossing detection is independent of callback registration:
            # the flight recorder must see pressure crossings even with no
            # on_pressure subscribers attached
            if not self._in_pressure and self._under_pressure_locked():
                self._in_pressure = True
                crossed = True
                if self._pressure_cbs:
                    fire = list(self._pressure_cbs)
            elif self._in_pressure and not self._under_pressure_locked():
                self._in_pressure = False
        if crossed:
            from ..observability import flight as _flight

            frec = _flight.recorder()
            if frec is not None:
                frec.note_pressure(self._tracked, self.limit_bytes())
        if fire:
            tracked, limit = self._tracked, self.limit_bytes()
            for cb in fire:
                try:
                    cb(tracked, limit)
                except Exception:
                    # a broken pressure callback must not fail the admit
                    registry().inc("subscriber_errors")

    def release(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._cond:
            self._tracked = max(self._tracked - nbytes, 0)
            registry().set_gauge("host_bytes_tracked", float(self._tracked))
            if self._in_pressure and not self._under_pressure_locked():
                self._in_pressure = False
            self._cond.notify_all()

    def tracked_bytes(self) -> int:
        with self._cond:
            return self._tracked

    def high_water_bytes(self) -> int:
        with self._cond:
            return self._high_water

    # ---- pressure ------------------------------------------------------------------
    def _pressure_threshold(self) -> int:
        from ..config import execution_config

        limit = self.limit_bytes()
        if limit <= 0:
            return 0
        return int(limit * execution_config().memory_pressure)

    def _under_pressure_locked(self) -> bool:
        t = self._pressure_threshold()
        return t > 0 and self._tracked >= t

    def under_pressure(self) -> bool:
        """True when tracked bytes sit at/over the pressure fraction of the
        budget — the signal streaming producers pace themselves against."""
        t = self._pressure_threshold()
        if t <= 0:
            return False
        with self._cond:
            return self._tracked >= t

    def wait_for_headroom(self, max_wait_s: float = _DEFAULT_MAX_WAIT_S) -> float:
        """Block while the ledger is under pressure, up to `max_wait_s`.

        Returns seconds actually stalled. Bounded by construction: this is
        producer PACING (a scan yielding to a spilling consumer), not an
        admission gate, so it can never deadlock a query whose budget is
        smaller than one operator's working set. Stalls are attributed via
        scan_backpressure_stalls / scan_stall_ms."""
        if not self.under_pressure():
            return 0.0
        import time

        t0 = time.perf_counter()
        deadline = t0 + max_wait_s
        with self._cond:
            while self._under_pressure_locked():
                now = time.perf_counter()
                if now >= deadline:
                    break
                self._cond.wait(min(0.02, deadline - now))
        stalled = time.perf_counter() - t0
        registry().inc("scan_backpressure_stalls")
        registry().inc("scan_stall_ms", max(int(stalled * 1000), 1))
        return stalled

    def on_pressure(self, cb: Callable[[int, int], None]) -> Callable[[], None]:
        """Register `cb(tracked_bytes, limit_bytes)`, fired once per upward
        crossing of the pressure threshold. Returns an unsubscribe callable."""
        with self._cond:
            self._pressure_cbs.append(cb)

        def _unsub() -> None:
            with self._cond:
                if cb in self._pressure_cbs:
                    self._pressure_cbs.remove(cb)

        return _unsub

    # ---- admission handles ---------------------------------------------------------
    def operator_budget(self) -> "LedgerBudget":
        """Admission handle for one memory-hungry operator instance. The
        returned handle is inert (no ledger/registry traffic) when no budget
        is in force — the zero-overhead path."""
        return LedgerBudget(self, self.limit_bytes())

    @contextlib.contextmanager
    def query_scope(self):
        """Per-query admission scope: bracket one query's execution to
        observe its ledger footprint — the peak tracked bytes while the
        scope was open (process-wide, so concurrent queries observe the
        shared peak, which is what admission sizing needs). Release safety
        does NOT depend on scopes: every operator budget releases in its own
        finally, unwound on failure/cancellation by the pipeline's
        generator-close propagation. Yields the handle (`peak_bytes()`)."""
        scope = QueryMemoryScope()
        with self._cond:
            self._scopes.append(scope)
            scope._peak = self._tracked
        try:
            yield scope
        finally:
            with self._cond:
                if scope in self._scopes:
                    self._scopes.remove(scope)

    # ---- introspection -------------------------------------------------------------
    def stats(self) -> dict:
        """Registry-consistent snapshot for bench/test assertions."""
        reg = registry()
        limit = self.limit_bytes()  # outside the ledger lock (reads config)
        with self._cond:
            tracked, high = self._tracked, self._high_water
        return {
            "host_limit_bytes": limit,
            "host_bytes_tracked": tracked,
            "host_bytes_high_water": high,
            "spill_bytes": reg.get("spill_bytes"),
            "spill_wire_bytes": reg.get("spill_wire_bytes"),
            "spill_runs": reg.get("spill_runs"),
            "scan_backpressure_stalls": reg.get("scan_backpressure_stalls"),
        }

    def clear(self) -> None:
        """Drop ledger state (test hook). Does not reset registry counters —
        memory.reset_counters() owns those."""
        with self._cond:
            self._tracked = 0
            self._high_water = 0
            self._auto_limit = None
            self._in_pressure = False
            self._pressure_cbs.clear()
            self._scopes.clear()
            registry().set_gauge("host_bytes_tracked", 0.0)
            registry().set_gauge("host_bytes_high_water", 0.0)


class QueryMemoryScope:
    """Handle yielded by HostMemoryManager.query_scope(): the ledger peak
    observed while the scope was open (process-wide — concurrent queries see
    a shared peak, which is exactly what admission sizing needs)."""

    __slots__ = ("_peak",)

    def __init__(self) -> None:
        self._peak = 0

    def peak_bytes(self) -> int:
        return self._peak


class LedgerBudget:
    """Byte-accounting handle for one blocking-operator instance, drawn
    against the shared process ledger.

    ``admit`` answers True while the LEDGER stays within the budget — so two
    concurrent queries each buffering 60% of the limit both flip to their
    spill strategies instead of jointly holding 120%. With no budget in
    force (limit <= 0) the handle is pure arithmetic: no manager calls, no
    registry writes (the zero-overhead contract the tier-1 guard pins).

    The operator owns release: ``release_all()`` when buffered bytes flush
    to spill files, and unconditionally (via ``close()``/finally) when the
    operator finishes, so an abandoned or failed query cannot leak ledger
    bytes and throttle the rest of the process."""

    __slots__ = ("_mgr", "limit", "used", "_over_counted")

    def __init__(self, mgr: HostMemoryManager, limit: int):
        self._mgr = mgr
        self.limit = limit
        self.used = 0
        self._over_counted = False

    def admit(self, nbytes: int) -> bool:
        """Account nbytes; True while within budget."""
        self.used += nbytes
        if self.limit <= 0:
            return True
        self._mgr.track(nbytes)
        ok = self._mgr.tracked_bytes() <= self.limit
        if not ok and not self._over_counted:
            self._over_counted = True
            registry().inc("host_over_budget_events")
        return ok

    def release(self, nbytes: int) -> None:
        """Return `nbytes` (clamped to current holdings) to the ledger — the
        incremental form spill loops use as each buffered batch lands on
        disk, so the ledger never claims freedom the process doesn't have."""
        n = min(nbytes, self.used)
        if n <= 0:
            return
        self.used -= n
        if self.limit > 0:
            self._mgr.release(n)

    def release_all(self) -> None:
        if self.limit > 0 and self.used:
            self._mgr.release(self.used)
        self.used = 0

    def close(self) -> None:
        self.release_all()

    def __enter__(self) -> "LedgerBudget":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def system_ram_bytes() -> int:
    """Total physical RAM, or 0 when the platform doesn't expose it."""
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page = os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        return 0
    if pages <= 0 or page <= 0:
        return 0
    return int(pages) * int(page)


_MANAGER = HostMemoryManager()


def manager() -> HostMemoryManager:
    """The process-wide host memory manager (one per driver / worker)."""
    return _MANAGER


def operator_budget() -> LedgerBudget:
    """Admission handle against the process ledger for one blocking operator
    (the re-homed successor of execution.memory.MemoryBudget)."""
    return _MANAGER.operator_budget()
