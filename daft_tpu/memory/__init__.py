"""Process-wide host memory management: the out-of-core execution tier.

This package is the HOST-side counterpart of the HBM ResidencyManager
(device/residency.py): one byte ledger every memory-hungry site admits
against (``manager()``), plus the disk-spill machinery (compressed Arrow IPC
spill files, Grace hash partitions, sorted runs) those sites switch to when
the ledger says no. ``execution/memory.py`` remains as the backward-
compatible view over this package.
"""

from .manager import (HostMemoryManager, LedgerBudget, QueryMemoryScope,
                      manager, operator_budget)
from .spill import (SpillFile, SpillPartitions, gc_stale_spills, reset_counters,
                    spill_root)

__all__ = [
    "HostMemoryManager", "LedgerBudget", "QueryMemoryScope", "manager",
    "operator_budget", "SpillFile", "SpillPartitions", "gc_stale_spills",
    "reset_counters", "spill_root",
]
