"""Disk spill for out-of-core operators: compressed Arrow IPC files with a
crash-safe lifecycle and (optionally) overlapped IO.

Format: Arrow IPC *stream* files with per-message body compression — the
same wire format the shuffle writer uses (distributed/shuffle.py), governed
by ``DAFT_TPU_SPILL_COMPRESSION`` (none|lz4|zstd, default lz4). Readers
stream batch-by-batch; the codec travels in the IPC message headers, so
mixed-codec spill dirs decode fine.

IO overlap (``DAFT_TPU_SPILL_IO_THREADS``, default 2): ``SpillFile.append``
enqueues the batch into a bounded per-file queue and returns; compression +
disk writes drain on a small process-wide IO pool, so the producer keeps
computing while its spill lands on disk. The queue is byte-capped AND its
pending bytes are tracked in the host memory ledger while a budget is in
force — async spill cannot defeat the budget by parking batches in RAM.
``finish()`` joins the queue and surfaces any deferred IO error;
``finish_async()`` schedules close+publish behind the pending writes without
blocking the caller. ``read(prefetch=N)`` decodes ahead on the same pool
into a bounded queue (``DAFT_TPU_SPILL_PREFETCH_BATCHES`` per reader, capped
globally), so a k-way merge overlaps k decompress streams with merge
compute. ``spill_io_threads=0`` is the zero-overhead/compat guard: the
synchronous single-threaded spill path, byte-for-byte the pre-async code,
touching neither pool, queue, nor the overlap counters.

Lifecycle discipline:

- every artifact name carries the OWNING PID (``s<pid>_…`` files,
  ``g<pid>_…`` Grace directories) under one spill root
  (``DAFT_TPU_SPILL_DIR`` or ``<tmp>/daft_tpu_spill``);
- writers append to a ``.tmp`` name and ``os.replace`` into the final name
  on finish (tmp + atomic publish), so a half-written file is never
  mistaken for a complete one;
- operators delete their files in ``finally`` blocks, which the pipeline's
  cancellation propagation unwinds on the producer thread (pipeline.py
  spawn_stage closes abandoned generators) — query failure and cancellation
  both GC their spill state in-process; ``delete()`` also abandons queued
  async writes and releases their ledger bytes;
- artifacts orphaned by a KILLED process (no finally ran) are swept by
  ``gc_stale_spills()``: any artifact whose embedded pid is dead is removed,
  including its ``.tmp`` in-progress names (the name pattern is FULLY
  anchored, so a junk name can never parse as someone's pid). The sweep runs
  once per process, lazily, at the first spill — a crashed run's droppings
  survive at most until the next spilling process starts.

Attribution: spill_batches / spill_bytes (logical) / spill_wire_bytes
(on-disk) / spill_files / spill_runs / spill_merge_passes / spill_dirs_gced
counters in the process registry (observability/metrics.py), plus the async
overlap split (spill_write_seconds vs spill_write_wall_seconds,
spill_read_seconds vs spill_read_wall_seconds, spill_prefetch_inflight) so
spill activity reaches QueryEnd.metrics, EXPLAIN ANALYZE, /metrics, and
bench JSON.
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile
import threading
import time
import uuid
from collections import deque
from typing import Callable, Iterator, List, Optional

import pyarrow as pa
import pyarrow.ipc as ipc

from ..core.recordbatch import RecordBatch
from ..core.series import Series
from ..observability.metrics import SPILL_COUNTER_NAMES, registry
from ..schema import Schema

_ATTR_TO_COUNTER = {"spills": "spill_batches", "spill_bytes": "spill_bytes"}


def __getattr__(name: str) -> int:
    # historical module attributes (memory.spills / memory.spill_bytes) as a
    # PEP 562 view over the registry — same pattern as ops/counters.py
    if name in _ATTR_TO_COUNTER:
        return registry().get(_ATTR_TO_COUNTER[name])
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def reset_counters() -> None:
    from ..observability.metrics import MEMORY_COUNTER_NAMES

    registry().reset(SPILL_COUNTER_NAMES + MEMORY_COUNTER_NAMES)


def spill_root() -> str:
    """Base directory spill artifacts land under."""
    from ..config import execution_config

    d = execution_config().spill_dir
    return d or os.path.join(tempfile.gettempdir(), "daft_tpu_spill")


# ---- stale-artifact GC ---------------------------------------------------------------

_GC_LOCK = threading.Lock()
_GC_DONE = False

# s<pid>_<hex>.arrow files, g<pid>_<hex> Grace dirs, and their .tmp
# in-progress variants. FULLY anchored: a prefix-only match would let an
# unrelated name that merely starts like an artifact parse out a bogus pid
# (and a dead bogus pid would delete a file we do not own).
_ARTIFACT_RE = re.compile(r"^[sg](\d+)_[0-9a-f]+(?:\.arrow(?:\.tmp)?)?$")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return True  # unknowable: never sweep what might be alive
    return True


def gc_stale_spills(root: Optional[str] = None) -> int:
    """Remove spill artifacts left behind by DEAD processes (pid parsed from
    the artifact name), INCLUDING their half-written ``.tmp`` names — a
    killed writer leaves its tmp behind and no finish() will ever publish
    it. Never touches a live process's files (published or .tmp). Returns
    the number of artifacts removed (also counted as spill_dirs_gced)."""
    root = root or spill_root()
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    removed = 0
    for name in names:
        m = _ARTIFACT_RE.match(name)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid == os.getpid() or _pid_alive(pid):
            continue
        path = os.path.join(root, name)
        try:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
            removed += 1
        except OSError:
            continue  # raced with another sweeper / fs trouble: leave it
    if removed:
        registry().inc("spill_dirs_gced", removed)
    return removed


def _gc_stale_once() -> None:
    global _GC_DONE
    with _GC_LOCK:
        if _GC_DONE:
            return
        _GC_DONE = True
    gc_stale_spills()


# ---- spill IO pool -------------------------------------------------------------------

_POOL_LOCK = threading.Lock()
_POOLS: dict = {}  # workers -> ThreadPoolExecutor (distinct knob values only)


def _io_pool(n: int):
    """The process-wide spill IO pool (created lazily at first async use).
    Keyed by size so a test overriding spill_io_threads gets a matching
    pool; real processes only ever create one."""
    with _POOL_LOCK:
        pool = _POOLS.get(n)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            pool = ThreadPoolExecutor(max_workers=n,
                                      thread_name_prefix="daft-spill-io")
            _POOLS[n] = pool
        return pool


def _queue_cap_bytes() -> int:
    """Byte cap for one spill file's pending-write queue: enough to keep the
    IO threads fed, small against the host budget so queued-but-unwritten
    spill cannot hold a meaningful slice of the ledger."""
    from .manager import manager

    limit = manager().limit_bytes()
    cap = 64 << 20
    if limit > 0:
        cap = min(cap, max(limit // 8, 1 << 20))
    return cap


# ---- prefetching reader --------------------------------------------------------------

# Global allowance for read-ahead batches QUEUED BEYOND the first per reader:
# every reader may always hold one decoded batch (progress guarantee), extra
# depth draws from this shared pool so fan-in x depth cannot multiply.
_PF_LOCK = threading.Lock()
_PF_EXTRA = 0
_PF_EXTRA_CAP = 64


def _pf_take_extra() -> bool:
    global _PF_EXTRA
    with _PF_LOCK:
        if _PF_EXTRA >= _PF_EXTRA_CAP:
            return False
        _PF_EXTRA += 1
        return True


def _pf_give_extra() -> None:
    global _PF_EXTRA
    with _PF_LOCK:
        _PF_EXTRA = max(_PF_EXTRA - 1, 0)


_EOF = object()


class _Prefetcher:
    """Pump one iterator on the spill IO pool into a bounded queue.

    The pump task is INCREMENTAL: it decodes while the queue has space and
    returns otherwise (the consumer reschedules it on drain), so k starved
    readers can share a 2-thread pool without wedging it — a pump never
    blocks a pool thread on a full queue."""

    def __init__(self, factory: Callable[[], Iterator], depth: int, pool,
                 counters: bool = True):
        self._factory = factory
        self._depth = max(int(depth), 1)
        self._pool = pool
        self._counters = counters
        self._cond = threading.Condition(threading.Lock())
        self._q: deque = deque()  # (item, holds_extra_token)
        self._eof = False
        self._err: Optional[BaseException] = None
        self._closed = False
        self._running = False
        self._it: Optional[Iterator] = None
        self._hw = 0

    def _pump(self) -> None:
        try:
            if self._it is None:
                self._it = self._factory()
            while True:
                token = False
                with self._cond:
                    if (self._closed or self._eof or self._err is not None
                            or len(self._q) >= self._depth):
                        return
                    if self._q:
                        token = _pf_take_extra()
                        if not token:
                            return  # global read-ahead budget exhausted
                t0 = time.perf_counter()
                try:
                    item = next(self._it, _EOF)
                except BaseException as e:  # noqa: BLE001 — crossed to the consumer, re-raised there
                    if token:
                        _pf_give_extra()
                    with self._cond:
                        self._err = e
                    return
                if self._counters:
                    registry().inc("spill_read_seconds",
                                   time.perf_counter() - t0)
                with self._cond:
                    if item is _EOF:
                        if token:
                            _pf_give_extra()
                        self._eof = True
                        return
                    if self._closed:
                        if token:
                            _pf_give_extra()
                        return
                    self._q.append((item, token))
                    if len(self._q) > self._hw:
                        self._hw = len(self._q)
                        if self._counters:
                            registry().set_gauge_max("spill_prefetch_inflight",
                                                     float(self._hw))
        finally:
            with self._cond:
                self._running = False
                self._cond.notify_all()

    def _schedule_locked(self) -> None:
        if (not self._running and not self._eof and self._err is None
                and not self._closed and len(self._q) < self._depth):
            self._running = True
            self._pool.submit(self._pump)

    def __iter__(self):
        return self

    def __next__(self):
        t0 = None
        with self._cond:
            while True:
                if self._q:
                    item, token = self._q.popleft()
                    break
                if self._err is not None:
                    raise self._err
                if self._eof:
                    raise StopIteration
                self._schedule_locked()
                if t0 is None:
                    t0 = time.perf_counter()
                self._cond.wait(0.05)
            self._schedule_locked()  # top the queue back up
        if token:
            _pf_give_extra()
        if t0 is not None and self._counters:
            registry().inc("spill_read_wall_seconds",
                           time.perf_counter() - t0)
        return item

    def close(self) -> None:
        with self._cond:
            self._closed = True
            for _item, token in self._q:
                if token:
                    _pf_give_extra()
            self._q.clear()
            self._cond.notify_all()
            while self._running:  # pump unwinds at its next queue check
                self._cond.wait(0.05)
        it, self._it = self._it, None
        if it is not None and hasattr(it, "close"):
            it.close()  # generator close -> the decode stream's finally runs


def prefetch_iter(factory: Callable[[], Iterator], depth: int,
                  io_threads: Optional[int] = None,
                  counters: bool = True) -> Iterator:
    """Stream ``factory()`` with up to ``depth`` items decoded ahead on the
    spill IO pool; falls back to plain iteration when read-ahead is off
    (depth or the pool size resolve to 0). Shared by spill read-back,
    shuffle reduce reads, and budgeted parquet scans."""
    if io_threads is None:
        from ..config import execution_config

        io_threads = execution_config().spill_io_threads
    if depth <= 0 or io_threads <= 0:
        yield from factory()
        return
    pf = _Prefetcher(factory, depth, _io_pool(io_threads), counters=counters)
    try:
        yield from pf
    finally:
        pf.close()


# ---- spill files ---------------------------------------------------------------------


def _ipc_options(compression: Optional[str]) -> ipc.IpcWriteOptions:
    if compression is None:
        from ..config import execution_config

        compression = execution_config().spill_compression
    return ipc.IpcWriteOptions(
        compression=None if compression == "none" else compression)


_FINISH = object()  # queue sentinel: close + publish behind pending writes


class SpillFile:
    """One append-only compressed Arrow IPC spill file with streaming
    read-back, tmp + atomic-publish lifecycle, and (spill_io_threads > 0)
    asynchronous writes drained on the process-wide spill IO pool."""

    def __init__(self, schema: Schema, spill_dir: Optional[str] = None,
                 compression: Optional[str] = None):
        _gc_stale_once()
        self.schema = schema
        d = spill_dir or spill_root()
        os.makedirs(d, exist_ok=True)
        self.path = os.path.join(d, f"s{os.getpid()}_{uuid.uuid4().hex[:10]}.arrow")
        self._tmp = self.path + ".tmp"
        self._opts = _ipc_options(compression)
        self._writer = None
        self._published = False
        self.rows = 0
        self.bytes_written = 0  # logical Arrow bytes appended
        from ..config import execution_config

        cfg = execution_config()
        # snapshot at construction: one file never mixes sync and async writes
        self._io_threads = cfg.spill_io_threads
        self._prefetch = cfg.spill_prefetch_batches
        # async-write state, allocated lazily at the first async append
        self._cond: Optional[threading.Condition] = None
        self._q: Optional[deque] = None  # (table|_FINISH, nbytes, ledgered)
        self._pending_bytes = 0
        self._draining = False
        self._io_err: Optional[BaseException] = None

    # ---- write side ----------------------------------------------------------------

    def append(self, batch: RecordBatch) -> None:
        if batch.num_rows == 0:
            return
        if self._io_threads <= 0:
            # synchronous path: byte-for-byte the pre-async behavior (the
            # DAFT_TPU_SPILL_IO_THREADS=0 compat guard)
            table = batch.to_arrow()
            if self._writer is None:
                registry().inc("spill_files")
                self._writer = ipc.new_stream(self._tmp, table.schema,
                                              options=self._opts)
            self._writer.write_table(table)
            self.rows += batch.num_rows
            nb = batch.size_bytes()
            self.bytes_written += nb
            registry().inc("spill_batches")
            registry().inc("spill_bytes", nb)
            return
        self._append_async(batch)

    def _append_async(self, batch: RecordBatch) -> None:
        from .manager import manager

        table = batch.to_arrow()
        nb = batch.size_bytes()
        if self._cond is None:
            self._cond = threading.Condition(threading.Lock())
            self._q = deque()
        cap = _queue_cap_bytes()
        stalled = 0.0
        with self._cond:
            t0 = time.perf_counter() if self._pending_bytes >= cap else 0.0
            while (self._pending_bytes >= cap and self._q
                   and self._io_err is None):
                self._cond.wait(0.05)
            if t0:
                stalled = time.perf_counter() - t0
            if self._io_err is not None:
                err = self._io_err
                raise RuntimeError(
                    f"deferred spill write failed: {err}") from err
            ledgered = 0
            mgr = manager()
            if mgr.limit_bytes() > 0:
                # pending spill is still resident host memory: keep it on the
                # ledger until the IO thread lands it, so async spill cannot
                # defeat the budget by parking batches in the queue
                mgr.track(nb)
                ledgered = nb
            self._q.append((table, nb, ledgered))
            self._pending_bytes += nb
            if not self._draining:
                self._draining = True
                _io_pool(self._io_threads).submit(self._drain)
        self.rows += batch.num_rows
        self.bytes_written += nb
        registry().inc("spill_batches")
        registry().inc("spill_bytes", nb)
        if stalled:
            registry().inc("spill_write_wall_seconds", stalled)

    def _drain(self) -> None:
        """IO-pool task: write queued tables in append order. One drainer per
        file at a time (the _draining flag), so writes stay ordered; the
        head item is only popped after its write completes, keeping
        backpressure honest."""
        from ..observability.runtime_stats import profile_span

        from .manager import manager

        while True:
            with self._cond:
                if self._io_err is not None or not self._q:
                    self._draining = False
                    self._cond.notify_all()
                    return
                item, nb, ledgered = self._q[0]
            t0 = time.perf_counter()
            try:
                if item is _FINISH:
                    self._close_and_publish()
                else:
                    with profile_span("spill.write", "spill",
                                      rows=item.num_rows):
                        if self._writer is None:
                            registry().inc("spill_files")
                            self._writer = ipc.new_stream(
                                self._tmp, item.schema, options=self._opts)
                        self._writer.write_table(item)
            except BaseException as e:  # noqa: BLE001 — deferred to append/finish on the producer
                with self._cond:
                    self._io_err = e
                    release = ledgered
                    while self._q:
                        _i, _nb, led = self._q.popleft()
                        release += led if _i is not item else 0
                    self._pending_bytes = 0
                    self._draining = False
                    self._cond.notify_all()
                if release:
                    manager().release(release)
                return
            if item is not _FINISH:
                registry().inc("spill_write_seconds",
                               time.perf_counter() - t0)
            with self._cond:
                if self._q and self._q[0][0] is item:
                    self._q.popleft()
                    self._pending_bytes -= nb
                else:
                    ledgered = 0  # delete() raced us and already released
                self._cond.notify_all()
            if ledgered:
                manager().release(ledgered)

    def _join_queue(self) -> None:
        """Wait for the async queue to drain; surface any deferred IO error.
        The wait is producer wall time the writes actually cost."""
        if self._cond is None:
            return
        t0 = time.perf_counter()
        with self._cond:
            while self._draining or self._q:
                self._cond.wait(0.05)
            err = self._io_err
        waited = time.perf_counter() - t0
        if waited > 0.0005:
            registry().inc("spill_write_wall_seconds", waited)
        if err is not None:
            raise RuntimeError(f"deferred spill write failed: {err}") from err

    def _close_and_publish(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if not self._published and os.path.exists(self._tmp):
            os.replace(self._tmp, self.path)
            self._published = True
            try:
                registry().inc("spill_wire_bytes", os.path.getsize(self.path))
            except OSError:
                pass  # the file vanished (concurrent delete): wire bytes stay advisory

    def finish(self) -> None:
        """Close the writer and atomically publish the file (joining any
        pending async writes first)."""
        self._join_queue()
        self._close_and_publish()

    def finish_async(self) -> None:
        """Schedule close+publish behind the pending async writes WITHOUT
        joining — the producer moves on (e.g. to sorting the next run) while
        this file's tail lands. A later finish()/read() joins and surfaces
        any deferred error. Synchronous files (io_threads=0) finish inline."""
        if self._cond is None:  # sync mode, or nothing was ever queued
            self.finish()
            return
        with self._cond:
            if self._io_err is None:
                self._q.append((_FINISH, 0, 0))
                if not self._draining:
                    self._draining = True
                    _io_pool(self._io_threads).submit(self._drain)

    # ---- read side -----------------------------------------------------------------

    def _decode_iter(self) -> Iterator[RecordBatch]:
        """Decode the published file batch-by-batch. The IPC stream carries
        ONE schema for all batches, so the arrow-schema comparison runs once
        and matching batches wrap zero-copy instead of paying a per-batch
        Table.from_batches + full cast."""
        try:
            target = self.schema.to_arrow()
        except ValueError:
            target = None  # python-object dtypes: always take the cast path
        fields = list(self.schema)
        with ipc.open_stream(self.path) as r:
            same: Optional[bool] = None
            for rb in r:
                if same is None:
                    same = target is not None and rb.schema.equals(target)
                if same:
                    cols = [Series.from_arrow(rb.column(i), f.name,
                                              dtype=f.dtype)
                            for i, f in enumerate(fields)]
                    yield RecordBatch(self.schema, cols, rb.num_rows)
                else:
                    yield RecordBatch.from_arrow(
                        pa.Table.from_batches([rb])).cast_to_schema(self.schema)

    def read(self, prefetch: Optional[int] = None) -> Iterator[RecordBatch]:
        """Stream batches back in append order, one at a time. With
        ``prefetch`` > 0 (default: the config knob when the IO pool is on),
        decode runs ahead on the spill IO pool into a bounded queue."""
        self.finish()
        if prefetch is None:
            prefetch = self._prefetch if self._io_threads > 0 else 0
        if self.rows == 0 or not os.path.exists(self.path):
            return
        if prefetch > 0 and self._io_threads > 0:
            from ..observability.runtime_stats import span_iter

            yield from span_iter(
                "spill.read", "spill",
                prefetch_iter(self._decode_iter, prefetch, self._io_threads))
        else:
            yield from self._decode_iter()

    # ---- lifecycle -----------------------------------------------------------------

    def delete(self) -> None:
        from .manager import manager

        if self._cond is not None:
            release = 0
            with self._cond:
                # abandon queued writes; keep the head if a drainer holds it
                # (it finishes that one write, then exits on the empty queue)
                while len(self._q) > (1 if self._draining else 0):
                    _item, nb, led = self._q.pop()
                    self._pending_bytes -= nb
                    release += led
                while self._draining:
                    self._cond.wait(0.05)
                while self._q:  # drainer exited between our two loops
                    _item, nb, led = self._q.popleft()
                    self._pending_bytes = max(self._pending_bytes - nb, 0)
                    release += led
                self._cond.notify_all()
            if release:
                manager().release(release)
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        for p in (self._tmp, self.path):
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass


class SpillPartitions:
    """K hash-partitioned spill files (Grace partitioning for agg/join/dedup/
    window), grouped under one per-operator directory so failure cleanup and
    the dead-pid sweep are a single rmtree."""

    def __init__(self, schema: Schema, k: int, spill_dir: Optional[str] = None):
        _gc_stale_once()
        base = spill_dir or spill_root()
        self.dir = os.path.join(base, f"g{os.getpid()}_{uuid.uuid4().hex[:10]}")
        os.makedirs(self.dir, exist_ok=True)
        self.k = k
        self.files: List[SpillFile] = [SpillFile(schema, self.dir)
                                       for _ in range(k)]

    @property
    def bytes_written(self) -> int:
        return sum(f.bytes_written for f in self.files)

    def append_partitioned(self, batch: RecordBatch, key_exprs) -> None:
        """Fan one batch across the K partition files. With the async spill
        pool on, each append is an enqueue and the K compress+write legs
        overlap on the IO pool instead of running as k serial writes on the
        producer thread."""
        from ..expressions.eval import eval_expression

        keys = [eval_expression(batch, e) for e in key_exprs]
        for j, piece in enumerate(batch.partition_by_hash(keys, self.k)):
            if piece.num_rows:
                self.files[j].append(piece)

    def delete(self) -> None:
        for f in self.files:
            f.delete()
        shutil.rmtree(self.dir, ignore_errors=True)
