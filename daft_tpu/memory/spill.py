"""Disk spill for out-of-core operators: compressed Arrow IPC files with a
crash-safe lifecycle.

Format: Arrow IPC *stream* files with per-message body compression — the
same wire format the shuffle writer uses (distributed/shuffle.py), governed
by ``DAFT_TPU_SPILL_COMPRESSION`` (none|lz4|zstd, default lz4). Readers
stream batch-by-batch; the codec travels in the IPC message headers, so
mixed-codec spill dirs decode fine.

Lifecycle discipline:

- every artifact name carries the OWNING PID (``s<pid>_…`` files,
  ``g<pid>_…`` Grace directories) under one spill root
  (``DAFT_TPU_SPILL_DIR`` or ``<tmp>/daft_tpu_spill``);
- writers append to a ``.tmp`` name and ``os.replace`` into the final name
  on finish (tmp + atomic publish), so a half-written file is never
  mistaken for a complete one;
- operators delete their files in ``finally`` blocks, which the pipeline's
  cancellation propagation unwinds on the producer thread (pipeline.py
  spawn_stage closes abandoned generators) — query failure and cancellation
  both GC their spill state in-process;
- artifacts orphaned by a KILLED process (no finally ran) are swept by
  ``gc_stale_spills()``: any artifact whose embedded pid is dead is removed.
  The sweep runs once per process, lazily, at the first spill — a crashed
  run's droppings survive at most until the next spilling process starts.

Attribution: spill_batches / spill_bytes (logical) / spill_wire_bytes
(on-disk) / spill_files / spill_runs / spill_merge_passes / spill_dirs_gced
counters in the process registry (observability/metrics.py), so spill
activity reaches QueryEnd.metrics, EXPLAIN ANALYZE, /metrics, and bench JSON.
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile
import threading
import uuid
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.ipc as ipc

from ..core.recordbatch import RecordBatch
from ..observability.metrics import SPILL_COUNTER_NAMES, registry
from ..schema import Schema

_ATTR_TO_COUNTER = {"spills": "spill_batches", "spill_bytes": "spill_bytes"}


def __getattr__(name: str) -> int:
    # historical module attributes (memory.spills / memory.spill_bytes) as a
    # PEP 562 view over the registry — same pattern as ops/counters.py
    if name in _ATTR_TO_COUNTER:
        return registry().get(_ATTR_TO_COUNTER[name])
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def reset_counters() -> None:
    from ..observability.metrics import MEMORY_COUNTER_NAMES

    registry().reset(SPILL_COUNTER_NAMES + MEMORY_COUNTER_NAMES)


def spill_root() -> str:
    """Base directory spill artifacts land under."""
    from ..config import execution_config

    d = execution_config().spill_dir
    return d or os.path.join(tempfile.gettempdir(), "daft_tpu_spill")


# ---- stale-artifact GC ---------------------------------------------------------------

_GC_LOCK = threading.Lock()
_GC_DONE = False

# s<pid>_<hex>.arrow files, g<pid>_<hex> Grace dirs (+ trailing .tmp variants)
_ARTIFACT_RE = re.compile(r"^[a-z](\d+)_[0-9a-f]+")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return True  # unknowable: never sweep what might be alive
    return True


def gc_stale_spills(root: Optional[str] = None) -> int:
    """Remove spill artifacts left behind by DEAD processes (pid parsed from
    the artifact name). Never touches a live process's files. Returns the
    number of artifacts removed (also counted as spill_dirs_gced)."""
    root = root or spill_root()
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    removed = 0
    for name in names:
        m = _ARTIFACT_RE.match(name)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid == os.getpid() or _pid_alive(pid):
            continue
        path = os.path.join(root, name)
        try:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
            removed += 1
        except OSError:
            continue  # raced with another sweeper / fs trouble: leave it
    if removed:
        registry().inc("spill_dirs_gced", removed)
    return removed


def _gc_stale_once() -> None:
    global _GC_DONE
    with _GC_LOCK:
        if _GC_DONE:
            return
        _GC_DONE = True
    gc_stale_spills()


# ---- spill files ---------------------------------------------------------------------


def _ipc_options(compression: Optional[str]) -> ipc.IpcWriteOptions:
    if compression is None:
        from ..config import execution_config

        compression = execution_config().spill_compression
    return ipc.IpcWriteOptions(
        compression=None if compression == "none" else compression)


class SpillFile:
    """One append-only compressed Arrow IPC spill file with streaming
    read-back and tmp + atomic-publish lifecycle."""

    def __init__(self, schema: Schema, spill_dir: Optional[str] = None,
                 compression: Optional[str] = None):
        _gc_stale_once()
        self.schema = schema
        d = spill_dir or spill_root()
        os.makedirs(d, exist_ok=True)
        self.path = os.path.join(d, f"s{os.getpid()}_{uuid.uuid4().hex[:10]}.arrow")
        self._tmp = self.path + ".tmp"
        self._opts = _ipc_options(compression)
        self._writer = None
        self._published = False
        self.rows = 0
        self.bytes_written = 0  # logical Arrow bytes appended

    def append(self, batch: RecordBatch) -> None:
        if batch.num_rows == 0:
            return
        table = batch.to_arrow()
        if self._writer is None:
            registry().inc("spill_files")
            self._writer = ipc.new_stream(self._tmp, table.schema,
                                          options=self._opts)
        self._writer.write_table(table)
        self.rows += batch.num_rows
        nb = batch.size_bytes()
        self.bytes_written += nb
        registry().inc("spill_batches")
        registry().inc("spill_bytes", nb)

    def finish(self) -> None:
        """Close the writer and atomically publish the file."""
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if not self._published and os.path.exists(self._tmp):
            os.replace(self._tmp, self.path)
            self._published = True
            try:
                registry().inc("spill_wire_bytes", os.path.getsize(self.path))
            except OSError:
                pass  # the file vanished (concurrent delete): wire bytes stay advisory

    def read(self) -> Iterator[RecordBatch]:
        """Stream batches back in append order, one at a time."""
        self.finish()
        if self.rows == 0 or not os.path.exists(self.path):
            return
        with ipc.open_stream(self.path) as r:
            for rb in r:
                yield RecordBatch.from_arrow(
                    pa.Table.from_batches([rb])).cast_to_schema(self.schema)

    def delete(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        for p in (self._tmp, self.path):
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass


class SpillPartitions:
    """K hash-partitioned spill files (Grace partitioning for agg/join/dedup/
    window), grouped under one per-operator directory so failure cleanup and
    the dead-pid sweep are a single rmtree."""

    def __init__(self, schema: Schema, k: int, spill_dir: Optional[str] = None):
        _gc_stale_once()
        base = spill_dir or spill_root()
        self.dir = os.path.join(base, f"g{os.getpid()}_{uuid.uuid4().hex[:10]}")
        os.makedirs(self.dir, exist_ok=True)
        self.k = k
        self.files: List[SpillFile] = [SpillFile(schema, self.dir)
                                       for _ in range(k)]

    @property
    def bytes_written(self) -> int:
        return sum(f.bytes_written for f in self.files)

    def append_partitioned(self, batch: RecordBatch, key_exprs) -> None:
        from ..expressions.eval import eval_expression

        keys = [eval_expression(batch, e) for e in key_exprs]
        for j, piece in enumerate(batch.partition_by_hash(keys, self.k)):
            if piece.num_rows:
                self.files[j].append(piece)

    def delete(self) -> None:
        for f in self.files:
            f.delete()
        shutil.rmtree(self.dir, ignore_errors=True)
