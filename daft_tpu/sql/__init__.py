"""SQL frontend (reference parity: src/daft-sql SQLPlanner + daft/sql/sql.py).

The package module is itself callable — `daft_tpu.sql("SELECT ...")` works even
though `daft_tpu.sql` is also the subpackage (import machinery binds the package
as an attribute of daft_tpu, shadowing the api-level function).
"""

from __future__ import annotations

import sys
import types


def sql(query: str, **bindings):
    try:
        from .planner import plan_sql
    except ImportError as e:
        raise NotImplementedError("SQL planner not built yet (see SQL milestone)") from e
    return plan_sql(query, bindings)


def sql_expr(text: str):
    try:
        from .parser import parse_expression
    except ImportError as e:
        raise NotImplementedError("SQL expression parser not built yet (see SQL milestone)") from e
    return parse_expression(text)


class _CallableModule(types.ModuleType):
    def __call__(self, query: str, **bindings):
        return sql(query, **bindings)


sys.modules[__name__].__class__ = _CallableModule
