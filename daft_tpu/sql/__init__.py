"""SQL frontend (reference parity: src/daft-sql SQLPlanner + daft/sql/sql.py).

The package module is itself callable — `daft_tpu.sql("SELECT ...")` works even
though `daft_tpu.sql` is also the subpackage (import machinery binds the package
as an attribute of daft_tpu, shadowing the api-level function).
"""

from __future__ import annotations

import sys
import types


def sql(query: str, **bindings):
    try:
        from .planner import plan_sql
    except ImportError as e:
        raise NotImplementedError("SQL planner not built yet (see SQL milestone)") from e
    # EXPLAIN PLACEMENT <select>: run the inner query and return the
    # placement-decision report (DataFrame.explain_placement) as a one-row
    # frame — the SQL face of the cost-model decision ledger
    stripped = query.lstrip()
    head = stripped[:30].upper().split()
    if head[:2] == ["EXPLAIN", "PLACEMENT"]:
        import daft_tpu

        parts = stripped.split(None, 2)
        if len(parts) < 3:
            raise ValueError(
                "EXPLAIN PLACEMENT requires a query to explain: "
                "EXPLAIN PLACEMENT SELECT ...")
        report = plan_sql(parts[2], bindings).explain_placement()
        return daft_tpu.from_pydict({"explain": report.split("\n")})
    return plan_sql(query, bindings)


def sql_expr(text: str):
    try:
        from .parser import parse_expression
    except ImportError as e:
        raise NotImplementedError("SQL expression parser not built yet (see SQL milestone)") from e
    return parse_expression(text)


class _CallableModule(types.ModuleType):
    def __call__(self, query: str, **bindings):
        return sql(query, **bindings)


sys.modules[__name__].__class__ = _CallableModule
