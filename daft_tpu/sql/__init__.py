"""SQL frontend (reference parity: src/daft-sql SQLPlanner + daft/sql/sql.py)."""

from __future__ import annotations


def sql(query: str, **bindings):
    try:
        from .planner import plan_sql
    except ImportError as e:
        raise NotImplementedError("SQL planner not built yet (see SQL milestone)") from e
    return plan_sql(query, bindings)


def sql_expr(text: str):
    try:
        from .parser import parse_expression
    except ImportError as e:
        raise NotImplementedError("SQL expression parser not built yet (see SQL milestone)") from e
    return parse_expression(text)
