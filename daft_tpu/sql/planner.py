"""SQL planner: Select AST → DataFrame (LogicalPlanBuilder).

Reference parity: src/daft-sql/src/planner.rs:113 (SQLPlanner::plan_sql) — table
resolution from bindings/session, scope-based qualified-column resolution,
equi-join key extraction from ON conjunctions, aggregate extraction with HAVING/
ORDER BY rewriting, set operations, CTEs and subqueries.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..expressions import Expression, col, lit
from ..expressions.expressions import AggExpr, Alias, BinaryOp, ColumnRef, WindowExpr
from .parser import JoinClause, OrderItem, Select, SelectItem, TableFactor, parse_select


def plan_sql(query: str, bindings: Dict[str, Any], session: Any = None):
    sel = parse_select(query)
    return SQLPlanner(bindings, session=session).plan(sel)


class Scope:
    """Maps table aliases → {source column → current column name in the DataFrame}."""

    def __init__(self):
        self.tables: Dict[str, Dict[str, str]] = {}

    def add(self, alias: Optional[str], columns: List[str], rename: Optional[Dict[str, str]] = None):
        rename = rename or {}
        m = {c: rename.get(c, c) for c in columns}
        if alias:
            self.tables[alias.lower()] = m

    def resolve(self, name: str) -> str:
        if "." in name:
            t, c = name.split(".", 1)
            tbl = self.tables.get(t.lower())
            if tbl is None:
                raise ValueError(f"unknown table alias {t!r}")
            if c not in tbl:
                raise ValueError(f"column {c!r} not found in table {t!r}")
            return tbl[c]
        return name

    def columns_of(self, alias: str) -> List[str]:
        tbl = self.tables.get(alias.lower())
        if tbl is None:
            raise ValueError(f"unknown table alias {alias!r}")
        return list(tbl.values())


class SQLPlanner:
    def __init__(self, bindings: Dict[str, Any], ctes: Optional[Dict[str, Any]] = None,
                 session: Any = None):
        self.bindings = bindings
        self.cte_frames: Dict[str, Any] = dict(ctes or {})
        self.session = session

    # ---- table resolution ---------------------------------------------------------
    def _resolve_table(self, name: str):
        key = name.lower()
        if key in self.cte_frames:
            return self.cte_frames[key]
        if name in self.bindings:
            return self.bindings[name]
        if key in self.bindings:
            return self.bindings[key]
        from ..session import current_session

        sess = self.session if self.session is not None else current_session()
        t = sess.get_table(name)
        if t is not None:
            return t
        raise ValueError(f"unknown table {name!r}")

    def _plan_factor(self, f: TableFactor, scope: Scope):
        if f.values is not None:
            import daft_tpu as dt
            from ..expressions.expressions import Literal

            ncols = len(f.values[0]) if f.values else 0
            names = f.col_names or [f"column{i + 1}" for i in range(ncols)]
            if any(len(r) != ncols for r in f.values):
                raise ValueError("VALUES rows have inconsistent arity")
            data = {}
            for i, n in enumerate(names):
                cells = []
                for r in f.values:
                    e = r[i]
                    if not isinstance(e, Literal):
                        raise ValueError("VALUES cells must be literals")
                    cells.append(e.value)
                data[n] = cells
            df = dt.from_pydict(data)
            scope.add(f.alias, df.column_names)
            return df
        if f.subquery is not None:
            df = SQLPlanner(self.bindings, self.cte_frames, session=self.session).plan(f.subquery)
            if f.col_names:
                df = df.select(*[col(c).alias(n)
                                 for c, n in zip(df.column_names, f.col_names)])
            scope.add(f.alias, df.column_names)
            return df
        df = self._resolve_table(f.name)
        scope.add(f.alias or f.name, df.column_names)
        return df

    # ---- expression resolution ----------------------------------------------------
    def _apply_where(self, df, where: Expression, scope: Scope):
        """Apply a WHERE clause; top-level [NOT] IN (SELECT ...) and [NOT]
        EXISTS (SELECT ...) conjuncts become semi/anti joins against the
        planned subquery, and scalar subqueries bind to joined columns
        (reference: unnest_subquery + push_down_anti_semi_join +
        planner.rs scalar-subquery planning)."""
        from ..expressions.expressions import BinaryOp, UnaryOp
        from .parser import ExistsSubquery, InSubquery, ScalarSubquery

        def conjuncts(e):
            if isinstance(e, BinaryOp) and e.op == "and":
                return conjuncts(e.left) + conjuncts(e.right)
            return [e]

        rest = []
        helpers: List[str] = []
        for c in conjuncts(where):
            negated = False
            node = c
            if isinstance(node, UnaryOp) and node.op == "not" \
                    and isinstance(node.child, (InSubquery, ExistsSubquery)):
                negated = True
                node = node.child
            if isinstance(node, ExistsSubquery):
                df = self._plan_exists(df, node.select, negated, scope)
                continue
            if not isinstance(node, InSubquery) and \
                    any(isinstance(n, ScalarSubquery) for n in node.walk()):
                df, node, h = self._bind_scalar_subqueries(df, node, scope)
                helpers.extend(h)
                rest.append(node)
                continue
            if isinstance(node, InSubquery):
                sub_df = SQLPlanner(self.bindings, self.cte_frames,
                                    session=self.session).plan(node.select)
                key = sub_df.column_names[0]
                left_key = self._resolve_expr(node.child, scope)
                if negated:
                    # SQL three-valued NOT IN: a NULL anywhere in the subquery
                    # makes the predicate NULL for every row (no rows pass);
                    # NULL left-side keys pass only against an EMPTY subquery
                    # (vacuously true). A plain anti join keeps both, so guard
                    # with a cross-joined (total, non-null) count before it.
                    # materialize once: the plan is consumed twice below (stats
                    # agg + anti join) and the executor has no subplan caching
                    sub_df = sub_df.collect()
                    stats = sub_df.agg(
                        lit(1).count("all").alias("__in_sub_cnt__"),
                        col(key).count().alias("__in_sub_nn__"))
                    guard = (col("__in_sub_cnt__") == col("__in_sub_nn__")) & (
                        (col("__in_sub_cnt__") == lit(0)) | left_key.not_null())
                    df = (df.join(stats, how="cross")
                            .where(guard)
                            .exclude("__in_sub_cnt__", "__in_sub_nn__")
                            .join(sub_df, left_on=left_key, right_on=key, how="anti"))
                else:
                    df = df.join(sub_df, left_on=left_key, right_on=key, how="semi")
            else:
                for n in node.walk():
                    if isinstance(n, InSubquery):
                        raise NotImplementedError(
                            "IN (subquery) only supported as a top-level AND conjunct")
                rest.append(node)
        if rest:
            pred = rest[0]
            for r in rest[1:]:
                pred = pred & r
            df = df.where(self._resolve_expr(pred, scope))
        if helpers:
            df = df.exclude(*[h for h in helpers if h in df.column_names])
        return df

    # ---- subquery unnesting --------------------------------------------------------
    def _inner_frame(self, sub_sel: Select):
        """Plan only the FROM/JOIN part of a subquery to learn which column
        names resolve inside it (cheap: plans are lazy)."""
        import daft_tpu as dt

        planner = SQLPlanner(self.bindings, self.cte_frames, session=self.session)
        inner_scope = Scope()
        if sub_sel.from_table is None:
            return dt.from_pydict({"__dummy__": [1]}), inner_scope
        inner_df = planner._plan_factor(sub_sel.from_table, inner_scope)
        for j in sub_sel.joins:
            inner_df = planner._plan_join(inner_df, j, inner_scope)
        return inner_df, inner_scope

    def _split_correlation(self, sub_sel: Select, inner_df, inner_scope: Scope,
                           outer_df, outer_scope: Scope):
        """Split the subquery WHERE into equality correlation pairs
        [(inner_ref, outer_ref)] and the remaining (inner-only) predicate.
        Raises for correlated predicates that aren't plain equalities —
        matching the reference's unnest_subquery coverage."""
        from ..expressions.expressions import BinaryOp

        inner_cols = set(inner_df.column_names)
        inner_aliases = set(inner_scope.tables.keys())
        outer_cols = set(outer_df.column_names)

        def is_inner(ref) -> bool:
            n = ref._name
            if "." in n:
                return n.split(".", 1)[0].lower() in inner_aliases
            return n in inner_cols

        def is_outer(ref) -> bool:
            n = ref._name
            if "." in n:
                return n.split(".", 1)[0].lower() in outer_scope.tables
            return n in outer_cols

        pairs, remaining = [], []
        if sub_sel.where is not None:
            for c in self._split_and(sub_sel.where):
                if (isinstance(c, BinaryOp) and c.op == "eq"
                        and isinstance(c.left, ColumnRef) and isinstance(c.right, ColumnRef)):
                    li, ri = is_inner(c.left), is_inner(c.right)
                    if li and not ri and is_outer(c.right):
                        pairs.append((c.left, c.right))
                        continue
                    if ri and not li and is_outer(c.left):
                        pairs.append((c.right, c.left))
                        continue
                for n in c.walk():
                    if isinstance(n, ColumnRef) and not is_inner(n) and is_outer(n):
                        raise NotImplementedError(
                            f"unsupported correlated subquery predicate: {c!r}")
                remaining.append(c)
        rem = None
        for r in remaining:
            rem = r if rem is None else rem & r
        return pairs, rem

    def _plan_exists(self, df, sub_sel: Select, negated: bool, scope: Scope):
        """[NOT] EXISTS (SELECT ...) -> semi/anti join on extracted correlation
        keys; uncorrelated EXISTS guards on the subquery's row count."""
        import dataclasses as dc

        from .parser import SelectItem

        inner_df, inner_scope = self._inner_frame(sub_sel)
        pairs, remaining = self._split_correlation(sub_sel, inner_df, inner_scope, df, scope)
        if not pairs:
            sub_df = SQLPlanner(self.bindings, self.cte_frames,
                                session=self.session).plan(sub_sel)
            cnt = sub_df.agg(lit(1).count("all").alias("__exists_cnt__"))
            cond = (col("__exists_cnt__") == lit(0)) if negated \
                else (col("__exists_cnt__") > lit(0))
            return df.join(cnt, how="cross").where(cond).exclude("__exists_cnt__")
        if sub_sel.group_by or sub_sel.having is not None:
            raise NotImplementedError("correlated EXISTS with GROUP BY/HAVING")
        if sub_sel.offset:
            raise NotImplementedError("correlated EXISTS with OFFSET")
        if sub_sel.limit == 0:
            # EXISTS over zero rows is constant FALSE
            return df if negated else df.limit(0)
        # LIMIT n >= 1 can't change "at least one row exists": safe to drop
        items = [SelectItem(inner_ref, f"__ek_{i}__") for i, (inner_ref, _o) in enumerate(pairs)]
        sub2 = dc.replace(sub_sel, items=items, where=remaining,
                          order_by=[], limit=None, offset=None, distinct=False)
        sub_df = SQLPlanner(self.bindings, self.cte_frames, session=self.session).plan(sub2)
        left_keys = [self._resolve_expr(o, scope) for _i, o in pairs]
        right_keys = [col(f"__ek_{i}__") for i in range(len(pairs))]
        return df.join(sub_df, left_on=left_keys, right_on=right_keys,
                       how="anti" if negated else "semi")

    def _bind_scalar_subqueries(self, df, expr: Expression, scope: Scope):
        """Replace each ScalarSubquery in `expr` with a column bound onto `df`:
        uncorrelated -> 1-row cross join; correlated -> grouped aggregate over
        the correlation keys, left-joined (missing keys yield NULL, matching
        SQL scalar-subquery semantics). Returns (df, expr, helper_columns)."""
        import dataclasses as dc

        from .parser import ScalarSubquery, SelectItem

        helpers: List[str] = []

        def rewrite(node):
            nonlocal df
            if not isinstance(node, ScalarSubquery):
                return None
            sub_sel = node.select
            n = self._scalar_counter = getattr(self, "_scalar_counter", 0) + 1
            alias = f"__scalar_{n}__"
            inner_df, inner_scope = self._inner_frame(sub_sel)
            pairs, remaining = self._split_correlation(sub_sel, inner_df, inner_scope, df, scope)
            if not pairs:
                sub_df = SQLPlanner(self.bindings, self.cte_frames,
                                    session=self.session).plan(sub_sel)
                first = sub_df.column_names[0]
                # SQL scalar semantics: >1 row is an error, 0 rows binds NULL —
                # materialize (cheap: a scalar) to enforce both
                probe = sub_df.select(col(first).alias(alias)).limit(2).collect()
                vals = probe.to_pydict()[alias]
                if len(vals) > 1:
                    raise ValueError("scalar subquery returned more than one row")
                dtype = probe.schema[alias].dtype
                import daft_tpu as dt

                one = dt.from_pydict({alias: [vals[0] if vals else None]})
                one = one.select(col(alias).cast(dtype))
                df = df.join(one, how="cross")
                helpers.append(alias)
                return ColumnRef(alias)
            if len(sub_sel.items) != 1 or sub_sel.items[0].expr is None:
                raise NotImplementedError(
                    "correlated scalar subquery must select exactly one expression")
            if sub_sel.group_by or sub_sel.having is not None:
                raise NotImplementedError("correlated scalar subquery with GROUP BY/HAVING")
            if sub_sel.limit is not None or sub_sel.offset:
                raise NotImplementedError(
                    "correlated scalar subquery with LIMIT/OFFSET (ORDER BY ... "
                    "LIMIT 1 idiom): rewrite as MAX/MIN")
            if not self._contains_agg(sub_sel.items[0].expr):
                raise NotImplementedError(
                    "correlated scalar subquery must select a single aggregate")
            key_aliases = [f"__sk_{n}_{i}__" for i in range(len(pairs))]
            items = [SelectItem(inner_ref, None) for inner_ref, _o in pairs]
            items.append(SelectItem(sub_sel.items[0].expr, alias))
            sub2 = dc.replace(sub_sel, items=items, where=remaining,
                              group_by=list(range(1, len(pairs) + 1)),
                              order_by=[], limit=None, offset=None, distinct=False)
            sub_df = SQLPlanner(self.bindings, self.cte_frames,
                                session=self.session).plan(sub2)
            names = sub_df.column_names  # group keys in order, then the aggregate
            sub_df = sub_df.select(
                *[col(names[i]).alias(key_aliases[i]) for i in range(len(pairs))],
                col(names[-1]).alias(alias))
            df = df.join(sub_df,
                         left_on=[self._resolve_expr(o, scope) for _i, o in pairs],
                         right_on=[col(a) for a in key_aliases], how="left")
            helpers.extend(key_aliases)
            helpers.append(alias)
            return ColumnRef(alias)

        new = expr.transform(rewrite)
        return df, new, helpers

    def _resolve_expr(self, e: Expression, scope: Scope) -> Expression:
        def rewrite(node):
            if isinstance(node, ColumnRef) and "." in node._name:
                return ColumnRef(scope.resolve(node._name))
            return None

        return e.transform(rewrite)

    # ---- main ---------------------------------------------------------------------
    def plan(self, sel: Select):
        from ..dataframe import DataFrame

        # CTEs visible to this select and nested ones
        planner = self
        if sel.ctes:
            planner = SQLPlanner(self.bindings, self.cte_frames, session=self.session)
            for name, sub in sel.ctes.items():
                planner.cte_frames[name] = SQLPlanner(self.bindings, planner.cte_frames, session=self.session).plan(sub)

        df = planner._plan_core(sel)

        for op, rhs in sel.set_ops:
            rdf = planner._plan_core(rhs)
            # SQL set ops align columns by POSITION: rename the right side's
            # columns to the left side's names (reference: set_expr planning)
            lnames, rnames = df.column_names, rdf.column_names
            if len(lnames) != len(rnames):
                raise ValueError(
                    f"set operation arms have {len(lnames)} vs {len(rnames)} columns")
            if lnames != rnames:
                rdf = rdf.select(*[col(rn).alias(ln)
                                   for ln, rn in zip(lnames, rnames)])
            if op == "union_all":
                df = df.concat(rdf)
            elif op == "union":
                df = df.concat(rdf).distinct()
            elif op == "intersect":
                df = df.intersect(rdf)
            else:
                df = df.except_distinct(rdf)

        df = planner._apply_order_limit(df, sel)
        return df

    def _plan_core(self, sel: Select):
        import daft_tpu as dt

        scope = Scope()
        if sel.from_table is None:
            if any(it.wildcard for it in sel.items):
                raise ValueError("SELECT * requires a FROM clause")
            # SELECT without FROM: single-row literal table
            df = dt.from_pydict({"__dummy__": [1]})
        else:
            df = self._plan_factor(sel.from_table, scope)

        for j in sel.joins:
            df = self._plan_join(df, j, scope)

        if sel.where is not None:
            df = self._apply_where(df, sel.where, scope)

        # expand wildcards
        items: List[SelectItem] = []
        for it in sel.items:
            if it.wildcard:
                cols = scope.columns_of(it.qualifier) if it.qualifier else df.column_names
                if not cols and sel.from_table is None:
                    raise ValueError("SELECT * with no FROM")
                for c in cols:
                    items.append(SelectItem(col(c), None))
            else:
                items.append(SelectItem(self._resolve_expr(it.expr, scope), it.alias))

        has_agg = any(self._contains_agg(it.expr) for it in items)
        if sel.grouping_sets is not None:
            df = self._plan_grouping_sets(df, sel, items, scope)
        elif sel.group_by or has_agg or (sel.having is not None):
            df = self._plan_aggregate(df, sel, items, scope)
        else:
            # ORDER BY may reference source columns dropped by the projection:
            # SQL scoping allows it, so sort before projecting in that case
            if sel.order_by and not sel.set_ops:
                out_names = {it.alias or it.expr.name() for it in items}
                in_names = set(df.column_names)
                needs_presort = any(
                    not isinstance(o.expr, int)
                    and any(c not in out_names for c in self._resolve_expr(o.expr, scope).referenced_columns())
                    for o in sel.order_by
                )
                if needs_presort:
                    alias_map = {it.alias: it.expr for it in items if it.alias}
                    keys, descs, nfs = [], [], []
                    for o in sel.order_by:
                        if isinstance(o.expr, int):
                            e = items[o.expr - 1].expr
                        else:
                            e = self._substitute_aliases(
                                self._resolve_expr(o.expr, scope), alias_map, in_names
                            )
                        keys.append(e)
                        descs.append(o.desc)
                        nfs.append(o.nulls_first if o.nulls_first is not None else o.desc)
                    df = df.sort(keys, descs, nfs)
                    sel.order_by = []
            df = df.select(*[self._item_expr(it) for it in items])

        if sel.distinct:
            df = df.distinct()
        return df

    def _substitute_aliases(self, e: Expression, alias_map: Dict[str, Expression], in_names) -> Expression:
        def rw(node):
            if isinstance(node, ColumnRef) and node._name not in in_names and node._name in alias_map:
                return alias_map[node._name]
            return None

        return e.transform(rw)

    def _item_expr(self, it: SelectItem) -> Expression:
        e = it.expr
        if it.alias:
            e = e.alias(it.alias)
        return e

    def _contains_agg(self, e: Expression) -> bool:
        if isinstance(e, WindowExpr):
            return False  # windowed aggs are not grouping aggs; skip the subtree
        if isinstance(e, AggExpr):
            return True
        return any(self._contains_agg(c) for c in e.children())

    # ---- joins --------------------------------------------------------------------
    def _plan_join(self, left_df, j: JoinClause, scope: Scope):
        right_scope = Scope()
        right_df = self._plan_factor(j.factor, right_scope)
        right_alias = j.factor.alias or j.factor.name

        if j.kind == "cross":
            out = left_df.join(right_df, how="cross")
            self._merge_scope_after_join(scope, right_scope, left_df, right_df, set())
            return out

        residual: Optional[Expression] = None
        if j.using:
            left_on = [col(c) for c in j.using]
            right_on = [col(c) for c in j.using]
        elif j.on is not None:
            left_on, right_on, residual = self._extract_equi_keys(j.on, scope, right_scope, left_df, right_df)
            if not left_on:
                if j.kind != "inner":
                    raise ValueError("non-equi join conditions only supported for INNER JOIN")
                out = left_df.join(right_df, how="cross")
                self._merge_scope_after_join(scope, right_scope, left_df, right_df, set())
                joined_scope_expr = self._resolve_expr_joined(j.on, scope)
                return out.where(joined_scope_expr)
            if residual is not None and j.kind != "inner":
                raise ValueError("residual join predicates only supported for INNER JOIN")
        else:
            raise ValueError("JOIN requires ON or USING")

        how = {"right_semi": "semi", "right_anti": "anti"}.get(j.kind, j.kind)
        if j.kind in ("right_semi", "right_anti"):
            out = right_df.join(left_df, left_on=right_on, right_on=left_on, how=how)
            scope.tables = right_scope.tables
            return out
        out = left_df.join(right_df, left_on=left_on, right_on=right_on, how=how)
        merged = {r.name() for l, r in zip(left_on, right_on) if l.name() == r.name()}
        if how in ("semi", "anti"):
            return out
        self._merge_scope_after_join(scope, right_scope, left_df, right_df, merged)
        if residual is not None:
            out = out.where(self._resolve_expr_joined(residual, scope))
        return out

    def _merge_scope_after_join(self, scope: Scope, right_scope: Scope, left_df, right_df, merged_keys):
        left_names = set(left_df.column_names)
        for alias, m in right_scope.tables.items():
            out_m = {}
            for src, cur in m.items():
                if cur in merged_keys:
                    out_m[src] = cur
                elif cur in left_names:
                    out_m[src] = f"right.{cur}"
                else:
                    out_m[src] = cur
            scope.tables[alias] = out_m

    def _resolve_expr_joined(self, e: Expression, scope: Scope) -> Expression:
        return self._resolve_expr(e, scope)

    def _extract_equi_keys(self, on: Expression, lscope: Scope, rscope: Scope, left_df, right_df):
        """Split an ON condition into equi-join keys + residual predicate."""
        left_cols = set(left_df.column_names)
        right_cols = set(right_df.column_names)

        conjuncts = self._split_and(on)
        left_on: List[Expression] = []
        right_on: List[Expression] = []
        residual: Optional[Expression] = None

        def side_of(name: str) -> Optional[str]:
            if "." in name:
                t = name.split(".", 1)[0].lower()
                if t in lscope.tables:
                    return "l"
                if t in rscope.tables:
                    return "r"
                return None
            inl = name in left_cols
            inr = name in right_cols
            if inl and not inr:
                return "l"
            if inr and not inl:
                return "r"
            return None

        for c in conjuncts:
            matched = False
            if isinstance(c, BinaryOp) and c.op == "eq":
                l, r = c.left, c.right
                if isinstance(l, ColumnRef) and isinstance(r, ColumnRef):
                    ls, rs = side_of(l._name), side_of(r._name)
                    if ls == "l" and rs == "r":
                        left_on.append(ColumnRef(lscope.resolve(l._name)))
                        right_on.append(ColumnRef(rscope.resolve(r._name)))
                        matched = True
                    elif ls == "r" and rs == "l":
                        left_on.append(ColumnRef(lscope.resolve(r._name)))
                        right_on.append(ColumnRef(rscope.resolve(l._name)))
                        matched = True
            if not matched:
                residual = c if residual is None else (residual & c)
        return left_on, right_on, residual

    def _split_and(self, e: Expression) -> List[Expression]:
        if isinstance(e, BinaryOp) and e.op == "and":
            return self._split_and(e.left) + self._split_and(e.right)
        return [e]

    # ---- aggregation --------------------------------------------------------------
    def _plan_grouping_sets(self, df, sel: Select, items: List[SelectItem],
                            scope: Scope):
        """ROLLUP / CUBE / GROUPING SETS: one grouped aggregate per key set,
        null-filling grouping columns absent from a set, unioned by name
        (reference: the sqlparser GroupByExpr lowering)."""
        import dataclasses as _dc

        from ..expressions import lit as _lit

        all_key_reprs = set()
        for ks in sel.grouping_sets:
            for k in ks:
                all_key_reprs.add(repr(self._resolve_expr(k, scope)))

        out = None
        for ks in sel.grouping_sets:
            resolved = [self._resolve_expr(k, scope) for k in ks]
            kreprs = {repr(k) for k in resolved}
            sub_items = []
            for it in items:
                r = repr(it.expr)
                if r in all_key_reprs and r not in kreprs:
                    name = it.alias or it.expr.name()
                    dtype = it.expr.to_field(df.schema).dtype
                    sub_items.append(SelectItem(_lit(None).cast(dtype).alias(name),
                                                it.alias or name))
                else:
                    sub_items.append(it)
            sub_sel = _dc.replace(sel, group_by=list(resolved), grouping_sets=None,
                                  order_by=[], limit=None, offset=None, set_ops=[])
            part = self._plan_aggregate(df, sub_sel, sub_items, scope)
            out = part if out is None else out.union_all_by_name(part)
        return out

    def _plan_aggregate(self, df, sel: Select, items: List[SelectItem], scope: Scope):
        # resolve group-by entries (positions refer to select items)
        group_exprs: List[Expression] = []
        for g in sel.group_by:
            if isinstance(g, int):
                group_exprs.append(items[g - 1].expr)
            else:
                group_exprs.append(self._resolve_expr(g, scope))

        # give grouping expressions stable output names: prefer the alias of a
        # matching select item, and disambiguate colliding derived names
        item_alias_by_repr = {repr(it.expr): it.alias for it in items if it.alias}
        named_groups: List[Tuple[str, Expression]] = []
        used_names: set = set()
        for g in group_exprs:
            name = item_alias_by_repr.get(repr(g)) or g.name()
            if name in used_names:
                i = 1
                while f"{name}_{i}" in used_names:
                    i += 1
                name = f"{name}_{i}"
            used_names.add(name)
            named_groups.append((name, g))

        # collect distinct aggregations from select items + having + order by
        agg_map: Dict[str, Tuple[str, AggExpr]] = {}

        def collect(e: Expression):
            for sub in e.walk():
                if isinstance(sub, AggExpr):
                    key = repr(sub)
                    if key not in agg_map:
                        agg_map[key] = (f"__agg_{len(agg_map)}", sub)

        for it in items:
            collect(it.expr)
        if sel.having is not None:
            collect(self._resolve_expr(sel.having, scope))
        for o in sel.order_by:
            if not isinstance(o.expr, int):
                collect(self._resolve_expr(o.expr, scope))

        aggs = [a.alias(internal) for internal, a in agg_map.values()]
        gb = [g.alias(n) for n, g in named_groups]
        df = df.groupby(*gb).agg(*aggs) if gb else df.agg(*aggs)

        group_names = {repr(g): n for n, g in named_groups}

        def replace(e: Expression) -> Expression:
            def rw(node):
                if isinstance(node, AggExpr):
                    internal, _ = agg_map[repr(node)]
                    return ColumnRef(internal)
                r = group_names.get(repr(node))
                if r is not None and not isinstance(node, ColumnRef):
                    return ColumnRef(r)
                return None

            return e.transform(rw)

        if sel.having is not None:
            df = df.where(replace(self._resolve_expr(sel.having, scope)))

        # rewrite ORDER BY in place so _apply_order_limit sees plain columns
        for o in sel.order_by:
            if not isinstance(o.expr, int):
                o.expr = replace(self._resolve_expr(o.expr, scope))

        final = []
        for it in items:
            e = replace(it.expr)
            if it.alias:
                e = e.alias(it.alias)
            final.append(e)
        out = df.select(*final)

        # ORDER BY may reference internal agg columns not in the final projection;
        # sort before dropping them when needed
        order_needs_internal = any(
            not isinstance(o.expr, int) and any(
                isinstance(s, ColumnRef) and s._name.startswith("__agg_") for s in o.expr.walk()
            )
            for o in sel.order_by
        )
        if order_needs_internal:
            keys = []
            descs = []
            nfs = []
            for o in sel.order_by:
                e = o.expr if not isinstance(o.expr, int) else final[o.expr - 1]
                keys.append(e)
                descs.append(o.desc)
                nfs.append(o.nulls_first if o.nulls_first is not None else o.desc)
            df = df.sort([k if isinstance(k, Expression) else col(k) for k in keys], descs, nfs)
            out = df.select(*final)
            sel.order_by = []
        return out

    # ---- order/limit ---------------------------------------------------------------
    def _apply_order_limit(self, df, sel: Select):
        if sel.order_by:
            keys: List[Expression] = []
            descs: List[bool] = []
            nfs: List[bool] = []
            out_names = df.column_names
            for o in sel.order_by:
                if isinstance(o.expr, int):
                    keys.append(col(out_names[o.expr - 1]))
                else:
                    keys.append(o.expr)
                descs.append(o.desc)
                nfs.append(o.nulls_first if o.nulls_first is not None else o.desc)
            df = df.sort(keys, descs, nfs)
        if sel.offset is not None:
            df = df.offset(sel.offset)
        if sel.limit is not None:
            df = df.limit(sel.limit)
        return df
