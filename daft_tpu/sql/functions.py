"""SQL function name → Expression mapping.

Reference parity: src/daft-sql/src/modules/* (per-domain SQL function modules
binding SQL names onto the engine's ScalarUDF registry).
"""

from __future__ import annotations

from typing import List

from ..datatype import DataType
from ..expressions import Expression, col, lit
from ..expressions.expressions import Cast, IfElse, Literal


def _lit_val(e: Expression):
    if isinstance(e, Literal):
        return e.value
    raise ValueError("expected a literal argument")


def build_sql_function(fname: str, args: List[Expression]) -> Expression:
    f = _SQL_FUNCS.get(fname)
    if f is not None:
        return f(args)
    # fall through to the engine registry under the lowercase name
    from ..functions.registry import has_function

    lname = fname.lower()
    if has_function(lname):
        return args[0]._fn(lname, *args[1:])
    raise ValueError(f"unknown SQL function {fname!r}")


def _coalesce(args):
    out = args[-1]
    for a in reversed(args[:-1]):
        out = IfElse(a.not_null(), a, out)
    return out.alias(args[0].name())


def _concat(args):
    out = args[0]
    for a in args[1:]:
        out = out._fn("utf8_concat", a)
    return out


def _substr(args):
    # SQL SUBSTR is 1-based; engine substr is 0-based
    start = args[1] - lit(1)
    length = args[2] if len(args) > 2 else None
    if length is None:
        return args[0]._fn("utf8_substr", start)
    return args[0]._fn("utf8_substr", start, length)


def _nullif(args):
    return IfElse(args[0] == args[1], lit(None), args[0]).alias(args[0].name())


def _ifnull(args):
    return args[0].fill_null(args[1])


def _if(args):
    return IfElse(args[0], args[1], args[2])


def _round(args):
    decimals = int(_lit_val(args[1])) if len(args) > 1 else 0
    return args[0].round(decimals)


def _log(args):
    if len(args) > 1:
        # SQL LOG(base, x)
        return args[1].log(float(_lit_val(args[0])))
    return args[0].log()


_SQL_FUNCS = {
    "ABS": lambda a: a[0].abs(),
    "CEIL": lambda a: a[0].ceil(),
    "CEILING": lambda a: a[0].ceil(),
    "FLOOR": lambda a: a[0].floor(),
    "ROUND": _round,
    "SQRT": lambda a: a[0].sqrt(),
    "EXP": lambda a: a[0].exp(),
    "LN": lambda a: a[0].log(),
    "LOG": _log,
    "LOG2": lambda a: a[0].log2(),
    "LOG10": lambda a: a[0].log10(),
    "POW": lambda a: a[0] ** a[1],
    "POWER": lambda a: a[0] ** a[1],
    "MOD": lambda a: a[0] % a[1],
    "SIGN": lambda a: a[0].sign(),
    "SIN": lambda a: a[0].sin(),
    "COS": lambda a: a[0].cos(),
    "TAN": lambda a: a[0].tan(),
    "ATAN": lambda a: a[0].arctan(),
    "ASIN": lambda a: a[0].arcsin(),
    "ACOS": lambda a: a[0].arccos(),
    "GREATEST": lambda a: _fold(a, lambda x, y: IfElse(x >= y, x, y)),
    "LEAST": lambda a: _fold(a, lambda x, y: IfElse(x <= y, x, y)),
    # strings
    "LOWER": lambda a: a[0].str.lower(),
    "UPPER": lambda a: a[0].str.upper(),
    "LENGTH": lambda a: a[0].str.length(),
    "CHAR_LENGTH": lambda a: a[0].str.length(),
    "TRIM": lambda a: a[0]._fn("utf8_strip"),
    "LTRIM": lambda a: a[0]._fn("utf8_lstrip"),
    "RTRIM": lambda a: a[0]._fn("utf8_rstrip"),
    "REVERSE": lambda a: a[0]._fn("utf8_reverse"),
    "REPLACE": lambda a: a[0]._fn("utf8_replace", _lit_val(a[1]), _lit_val(a[2])),
    "SUBSTR": _substr,
    "SUBSTRING": _substr,
    "LEFT": lambda a: a[0]._fn("utf8_left", a[1]),
    "RIGHT": lambda a: a[0]._fn("utf8_right", a[1]),
    "REPEAT": lambda a: a[0]._fn("utf8_repeat", a[1]),
    "LPAD": lambda a: a[0]._fn("utf8_lpad", _lit_val(a[1]), _lit_val(a[2]) if len(a) > 2 else " "),
    "RPAD": lambda a: a[0]._fn("utf8_rpad", _lit_val(a[1]), _lit_val(a[2]) if len(a) > 2 else " "),
    "CONTAINS": lambda a: a[0]._fn("utf8_contains", _lit_val(a[1])),
    "STARTS_WITH": lambda a: a[0]._fn("utf8_startswith", _lit_val(a[1])),
    "ENDS_WITH": lambda a: a[0]._fn("utf8_endswith", _lit_val(a[1])),
    "REGEXP_MATCH": lambda a: a[0]._fn("utf8_match", _lit_val(a[1])),
    "SPLIT": lambda a: a[0]._fn("utf8_split", _lit_val(a[1])),
    "CONCAT": _concat,
    "CONCAT_WS": lambda a: _fold(a[1:], lambda x, y: x._fn("utf8_concat", a[0])._fn("utf8_concat", y)),
    # conditionals
    "COALESCE": _coalesce,
    "NULLIF": _nullif,
    "IFNULL": _ifnull,
    "NVL": _ifnull,
    "IF": _if,
    "IIF": _if,
    # temporal
    "YEAR": lambda a: a[0]._fn("dt_year"),
    "MONTH": lambda a: a[0]._fn("dt_month"),
    "DAY": lambda a: a[0]._fn("dt_day"),
    "HOUR": lambda a: a[0]._fn("dt_hour"),
    "MINUTE": lambda a: a[0]._fn("dt_minute"),
    "SECOND": lambda a: a[0]._fn("dt_second"),
    "DAYOFWEEK": lambda a: a[0]._fn("dt_day_of_week"),
    "DAYOFYEAR": lambda a: a[0]._fn("dt_day_of_year"),
    "WEEKOFYEAR": lambda a: a[0]._fn("dt_week_of_year"),
    "DATE_TRUNC": lambda a: a[1]._fn("dt_truncate", interval=f"1 {_lit_val(a[0])}"),
    "TO_DATE": lambda a: a[0]._fn("utf8_to_date", _lit_val(a[1]) if len(a) > 1 else "%Y-%m-%d"),
    "DATE": lambda a: Cast(a[0], DataType.date()),
    # list
    "ARRAY_LENGTH": lambda a: a[0]._fn("list_length"),
    "LIST_CONTAINS": lambda a: a[0]._fn("list_contains", a[1]),
    "ARRAY_CONTAINS": lambda a: a[0]._fn("list_contains", a[1]),
    # misc
    "HASH": lambda a: a[0].hash(),
    "MINHASH": lambda a: a[0].minhash(),
}


def _fold(args, f):
    out = args[0]
    for a in args[1:]:
        out = f(out, a)
    return out
