"""SQL tokenizer.

Reference parity: src/daft-sql (which uses the sqlparser crate); here a
self-contained lexer producing a flat token stream for the Pratt parser.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass
class Token:
    kind: str   # 'ident', 'number', 'string', 'op', 'punct', 'eof'
    value: str
    pos: int

    def upper(self) -> str:
        return self.value.upper()


_MULTI_OPS = ("<=", ">=", "<>", "!=", "||", "::")
_SINGLE_OPS = "+-*/%<>=^"
_PUNCT = "(),.;[]"


class Tokenizer:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0

    def tokenize(self) -> List[Token]:
        out: List[Token] = []
        while True:
            t = self._next()
            out.append(t)
            if t.kind == "eof":
                return out

    def _peek_ch(self, off: int = 0) -> str:
        p = self.pos + off
        return self.text[p] if p < len(self.text) else ""

    def _next(self) -> Token:
        text, n = self.text, len(self.text)
        while self.pos < n and text[self.pos].isspace():
            self.pos += 1
        # comments
        if text.startswith("--", self.pos):
            while self.pos < n and text[self.pos] != "\n":
                self.pos += 1
            return self._next()
        if text.startswith("/*", self.pos):
            end = text.find("*/", self.pos + 2)
            self.pos = n if end < 0 else end + 2
            return self._next()
        if self.pos >= n:
            return Token("eof", "", self.pos)
        start = self.pos
        ch = text[self.pos]
        # string literal
        if ch == "'":
            self.pos += 1
            buf = []
            while self.pos < n:
                c = text[self.pos]
                if c == "'":
                    if self._peek_ch(1) == "'":  # escaped quote
                        buf.append("'")
                        self.pos += 2
                        continue
                    self.pos += 1
                    return Token("string", "".join(buf), start)
                buf.append(c)
                self.pos += 1
            raise ValueError(f"unterminated string literal at {start}")
        # quoted identifier
        if ch == '"' or ch == "`":
            quote = ch
            self.pos += 1
            end = text.find(quote, self.pos)
            if end < 0:
                raise ValueError(f"unterminated quoted identifier at {start}")
            val = text[self.pos:end]
            self.pos = end + 1
            return Token("ident", val, start)
        # number
        if ch.isdigit() or (ch == "." and self._peek_ch(1).isdigit()):
            p = self.pos
            seen_dot = False
            seen_e = False
            while p < n:
                c = text[p]
                if c.isdigit():
                    p += 1
                elif c == "." and not seen_dot and not seen_e:
                    seen_dot = True
                    p += 1
                elif c in "eE" and not seen_e and p + 1 < n and (text[p + 1].isdigit() or text[p + 1] in "+-"):
                    seen_e = True
                    p += 1
                    if text[p] in "+-":
                        p += 1
                else:
                    break
            val = text[self.pos:p]
            self.pos = p
            return Token("number", val, start)
        # identifier / keyword
        if ch.isalpha() or ch == "_":
            p = self.pos
            while p < n and (text[p].isalnum() or text[p] == "_"):
                p += 1
            val = text[self.pos:p]
            self.pos = p
            return Token("ident", val, start)
        # multi-char operators
        for m in _MULTI_OPS:
            if text.startswith(m, self.pos):
                self.pos += len(m)
                return Token("op", m, start)
        if ch in _SINGLE_OPS:
            self.pos += 1
            return Token("op", ch, start)
        if ch in _PUNCT:
            self.pos += 1
            return Token("punct", ch, start)
        raise ValueError(f"unexpected character {ch!r} at position {self.pos}")


def tokenize(text: str) -> List[Token]:
    return Tokenizer(text).tokenize()
