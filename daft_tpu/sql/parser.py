"""SQL parser: tokens → Expression IR + a small SELECT-statement AST.

Reference parity: src/daft-sql/src/planner.rs (expression/statement planning over
the sqlparser AST); here parsing builds our Expression nodes directly via a Pratt
parser, and SELECT structure lands in Select/TableRef dataclasses for the planner.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ..datatype import DataType
from ..expressions import Expression, col, lit
from ..expressions.expressions import AggExpr, Alias, Between, BinaryOp, Cast, IfElse, IsIn, UnaryOp, _UnboundWindowFn
from .tokenizer import Token, tokenize

_KEYWORDS_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "UNION", "ALL",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "CROSS", "ON", "AS", "BY",
    "ASC", "DESC", "NULLS", "FIRST", "LAST", "AND", "OR", "NOT", "THEN", "ELSE",
    "END", "WHEN", "SELECT", "DISTINCT", "WITH", "USING", "SEMI", "ANTI", "INTERSECT", "EXCEPT",
}

# binding powers for binary operators (Pratt)
_BP = {
    "OR": 10,
    "AND": 20,
    "=": 40, "==": 40, "<>": 40, "!=": 40, "<": 40, "<=": 40, ">": 40, ">=": 40,
    "LIKE": 40, "ILIKE": 40, "IN": 40, "BETWEEN": 40, "IS": 40,
    "||": 50,
    "+": 60, "-": 60,
    "*": 70, "/": 70, "%": 70,
    "^": 80,
    "::": 90,
}

_AGG_FUNCS = {
    "SUM": "sum", "AVG": "mean", "MEAN": "mean", "MIN": "min", "MAX": "max",
    "COUNT": "count", "STDDEV": "stddev", "STDDEV_SAMP": "stddev", "VAR": "var",
    "VARIANCE": "var", "ANY_VALUE": "any_value", "SKEW": "skew",
    "BOOL_AND": "bool_and", "BOOL_OR": "bool_or",
    "APPROX_COUNT_DISTINCT": "approx_count_distinct",
    "LIST_AGG": "list", "ARRAY_AGG": "list",
}

_WINDOW_RANK_FUNCS = {"ROW_NUMBER", "RANK", "DENSE_RANK", "PERCENT_RANK", "CUME_DIST", "NTILE"}

_TYPE_NAMES = {
    "INT": DataType.int32, "INTEGER": DataType.int32, "INT4": DataType.int32,
    "BIGINT": DataType.int64, "INT8": DataType.int64, "SMALLINT": DataType.int16,
    "TINYINT": DataType.int8, "FLOAT": DataType.float32, "REAL": DataType.float32,
    "DOUBLE": DataType.float64, "FLOAT8": DataType.float64, "FLOAT4": DataType.float32,
    "TEXT": DataType.string, "STRING": DataType.string, "VARCHAR": DataType.string,
    "BOOL": DataType.bool, "BOOLEAN": DataType.bool, "DATE": DataType.date,
    "BINARY": DataType.binary, "BYTES": DataType.binary,
}


class InSubquery(Expression):
    """`expr IN (SELECT ...)` marker (reference: daft-dsl Expr::InSubquery).
    Never evaluated directly — the planner rewrites it into a semi join (anti
    under NOT)."""

    def __init__(self, child: Expression, select):
        self.child = child
        self.select = select

    def name(self) -> str:
        return self.child.name()

    def children(self):
        return [self.child]

    def with_children(self, children):
        return InSubquery(children[0], self.select)

    def to_field(self, schema):
        from ..datatype import Field

        return Field(self.name(), DataType.bool())

    def __repr__(self):
        return f"{self.child!r} IN (<subquery>)"


class ExistsSubquery(Expression):
    """`EXISTS (SELECT ...)` marker (reference: daft-dsl Expr::Exists +
    unnest_subquery lowering). Never evaluated directly — the planner rewrites
    it into a semi join (anti under NOT), extracting equality correlation
    predicates from the subquery's WHERE as the join keys."""

    def __init__(self, select):
        self.select = select

    def name(self) -> str:
        return "exists"

    def children(self):
        return []

    def with_children(self, children):
        return self

    def to_field(self, schema):
        from ..datatype import Field

        return Field("exists", DataType.bool())

    def __repr__(self):
        return "EXISTS (<subquery>)"


class ScalarSubquery(Expression):
    """`(SELECT <agg> ...)` used as a value (reference: daft-sql planner
    scalar-subquery planning). The planner binds it to a column: uncorrelated
    subqueries cross-join a 1-row frame; correlated ones become a grouped
    aggregate left-joined on the correlation keys."""

    def __init__(self, select):
        self.select = select

    def name(self) -> str:
        return "__scalar_subquery__"

    def children(self):
        return []

    def with_children(self, children):
        return self

    def to_field(self, schema):
        raise ValueError("scalar subquery must be bound by the planner before evaluation")

    def __repr__(self):
        return "(<scalar subquery>)"


@dataclasses.dataclass
class SelectItem:
    expr: Optional[Expression]   # None for wildcard
    alias: Optional[str]
    wildcard: bool = False
    qualifier: Optional[str] = None  # t.* wildcard


@dataclasses.dataclass
class TableFactor:
    name: Optional[str] = None          # table name
    subquery: Optional["Select"] = None
    alias: Optional[str] = None
    values: Optional[list] = None       # VALUES rows (lists of Expressions)
    col_names: Optional[list] = None    # alias column list: x(a, b)


@dataclasses.dataclass
class JoinClause:
    factor: TableFactor
    kind: str                    # inner/left/right/outer/cross/semi/anti
    on: Optional[Expression]
    using: Optional[List[str]] = None


@dataclasses.dataclass
class OrderItem:
    expr: Expression
    desc: bool = False
    nulls_first: Optional[bool] = None


@dataclasses.dataclass
class Select:
    items: List[SelectItem] = dataclasses.field(default_factory=list)
    distinct: bool = False
    from_table: Optional[TableFactor] = None
    joins: List[JoinClause] = dataclasses.field(default_factory=list)
    where: Optional[Expression] = None
    group_by: List[Any] = dataclasses.field(default_factory=list)  # Expression | int position
    grouping_sets: Optional[List[List[Any]]] = None  # ROLLUP/CUBE/GROUPING SETS
    having: Optional[Expression] = None
    order_by: List[OrderItem] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    ctes: Dict[str, "Select"] = dataclasses.field(default_factory=dict)
    set_ops: List[Tuple[str, "Select"]] = dataclasses.field(default_factory=list)  # (op, rhs)


class Parser:
    def __init__(self, text: str):
        self.tokens = tokenize(text)
        self.i = 0

    # ---- token helpers -----------------------------------------------------------
    def peek(self, off: int = 0) -> Token:
        j = min(self.i + off, len(self.tokens) - 1)
        return self.tokens[j]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper() in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            raise ValueError(f"expected {kw} at position {self.peek().pos}, got {self.peek().value!r}")

    def at(self, kind: str, value: Optional[str] = None) -> bool:
        t = self.peek()
        return t.kind == kind and (value is None or t.value == value)

    def eat(self, kind: str, value: Optional[str] = None) -> bool:
        if self.at(kind, value):
            self.next()
            return True
        return False

    def expect(self, kind: str, value: Optional[str] = None) -> Token:
        if not self.at(kind, value):
            t = self.peek()
            raise ValueError(f"expected {value or kind} at position {t.pos}, got {t.value!r}")
        return self.next()

    # ---- expressions --------------------------------------------------------------
    def parse_expr(self, min_bp: int = 0) -> Expression:
        lhs = self._prefix()
        while True:
            t = self.peek()
            opname = None
            if t.kind == "op" and t.value in _BP:
                opname = t.value
            elif t.kind == "ident" and t.upper() in ("AND", "OR", "LIKE", "ILIKE", "IN", "BETWEEN", "IS", "NOT"):
                opname = t.upper()
            if opname is None:
                return lhs
            if opname == "NOT":
                # NOT IN / NOT LIKE / NOT BETWEEN
                nxt = self.peek(1)
                if not (nxt.kind == "ident" and nxt.upper() in ("IN", "LIKE", "ILIKE", "BETWEEN")):
                    return lhs
                if _BP[nxt.upper()] < min_bp:
                    return lhs
                self.next()  # NOT
                inner_op = self.next().upper()
                lhs = ~self._postfix_op(lhs, inner_op)
                continue
            bp = _BP[opname]
            if bp < min_bp:
                return lhs
            self.next()
            if opname in ("LIKE", "ILIKE", "IN", "BETWEEN", "IS"):
                lhs = self._postfix_op(lhs, opname)
                continue
            if opname == "::":
                lhs = Cast(lhs, self._parse_type())
                continue
            rhs = self.parse_expr(bp + 1)
            lhs = self._binary(opname, lhs, rhs)

    def _binary(self, op: str, l: Expression, r: Expression) -> Expression:
        if op == "OR":
            return l | r
        if op == "AND":
            return l & r
        if op in ("=", "=="):
            return l == r
        if op in ("<>", "!="):
            return l != r
        if op == "<":
            return l < r
        if op == "<=":
            return l <= r
        if op == ">":
            return l > r
        if op == ">=":
            return l >= r
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return l / r
        if op == "%":
            return l % r
        if op == "^":
            return l ** r
        if op == "||":
            return l._fn("utf8_concat", r)
        raise ValueError(f"unhandled operator {op}")

    def _postfix_op(self, lhs: Expression, op: str) -> Expression:
        if op in ("LIKE", "ILIKE"):
            pattern = self.parse_expr(_BP["LIKE"] + 1)
            fname = "utf8_like" if op == "LIKE" else "utf8_ilike"
            from ..expressions.expressions import Literal

            if not isinstance(pattern, Literal):
                raise ValueError("LIKE pattern must be a string literal")
            return lhs._fn(fname, pattern.value)
        if op == "IN":
            self.expect("punct", "(")
            if self.at_kw("SELECT"):
                sub = self._parse_select()
                self.expect("punct", ")")
                return InSubquery(lhs, sub)
            items = [self.parse_expr()]
            while self.eat("punct", ","):
                items.append(self.parse_expr())
            self.expect("punct", ")")
            return IsIn(lhs, items)
        if op == "BETWEEN":
            lo = self.parse_expr(_BP["BETWEEN"] + 1)
            self.expect_kw("AND")
            hi = self.parse_expr(_BP["BETWEEN"] + 1)
            return Between(lhs, lo, hi)
        if op == "IS":
            negate = self.eat_kw("NOT")
            if self.eat_kw("NULL"):
                return lhs.not_null() if negate else lhs.is_null()
            # IS [NOT] TRUE/FALSE: three-valued — NULL IS TRUE = false (never null)
            if self.eat_kw("TRUE"):
                e = lhs.eq_null_safe(lit(True))
                return ~e if negate else e
            if self.eat_kw("FALSE"):
                e = lhs.eq_null_safe(lit(False))
                return ~e if negate else e
            raise ValueError("expected NULL/TRUE/FALSE after IS")
        raise ValueError(op)

    def _prefix(self) -> Expression:
        t = self.peek()
        if t.kind == "op" and t.value == "-":
            self.next()
            return -self.parse_expr(65)
        if t.kind == "op" and t.value == "+":
            self.next()
            return self.parse_expr(65)
        if t.kind == "ident" and t.upper() == "NOT":
            self.next()
            return ~self.parse_expr(25)
        if t.kind == "punct" and t.value == "(":
            self.next()
            if self.at_kw("SELECT"):
                sub = self._parse_select()
                self.expect("punct", ")")
                return ScalarSubquery(sub)
            e = self.parse_expr()
            self.expect("punct", ")")
            return e
        if t.kind == "number":
            self.next()
            txt = t.value
            if "." in txt or "e" in txt or "E" in txt:
                return lit(float(txt))
            return lit(int(txt))
        if t.kind == "string":
            self.next()
            return lit(t.value)
        if t.kind == "ident":
            up = t.upper()
            if up == "EXISTS":
                self.next()
                self.expect("punct", "(")
                sub = self._parse_select()
                self.expect("punct", ")")
                return ExistsSubquery(sub)
            if up == "NULL":
                self.next()
                return lit(None)
            if up == "TRUE":
                self.next()
                return lit(True)
            if up == "FALSE":
                self.next()
                return lit(False)
            if up == "CASE":
                return self._parse_case()
            if up == "CAST":
                self.next()
                self.expect("punct", "(")
                e = self.parse_expr()
                self.expect_kw("AS")
                dt = self._parse_type()
                self.expect("punct", ")")
                return Cast(e, dt)
            if up == "DATE" and self.peek(1).kind == "string":
                self.next()
                import datetime as _dt

                s = self.next().value
                return lit(_dt.date.fromisoformat(s))
            if up == "TIMESTAMP" and self.peek(1).kind == "string":
                self.next()
                import datetime as _dt

                s = self.next().value
                return lit(_dt.datetime.fromisoformat(s))
            if up == "INTERVAL":
                self.next()
                import datetime as _dt

                spec = self.expect("string").value.strip()
                parts = spec.split()
                if len(parts) == 2:
                    n, unit = parts
                elif self.peek().kind == "ident":
                    n, unit = spec, self.next().value
                else:
                    raise ValueError(f"malformed INTERVAL {spec!r}")
                n = float(n)
                unit = unit.rstrip("sS").lower()
                fixed = {"day": 86400.0, "week": 7 * 86400.0, "hour": 3600.0,
                         "minute": 60.0, "second": 1.0, "millisecond": 1e-3}
                if unit in fixed:
                    return lit(_dt.timedelta(seconds=n * fixed[unit]))
                raise NotImplementedError(
                    f"INTERVAL unit {unit!r}: calendar units (month/year) are not "
                    "fixed durations; use the dt namespace (e.g. add via "
                    "datetime arithmetic in the DataFrame API)")
            # function call?
            if self.peek(1).kind == "punct" and self.peek(1).value == "(":
                return self._parse_function_call()
            # qualified / bare column
            self.next()
            name = t.value
            if self.eat("punct", "."):
                if self.at("op", "*"):
                    raise ValueError("qualified wildcard only allowed in SELECT list")
                sub = self.expect("ident").value
                return col(f"{name}.{sub}")
            return col(name)
        raise ValueError(f"unexpected token {t.value!r} at {t.pos}")

    def _parse_case(self) -> Expression:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        branches = []
        while self.eat_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            val = self.parse_expr()
            if operand is not None:
                cond = operand == cond
            branches.append((cond, val))
        default = lit(None)
        if self.eat_kw("ELSE"):
            default = self.parse_expr()
        self.expect_kw("END")
        out = default
        for cond, val in reversed(branches):
            out = IfElse(cond, val, out)
        return out

    def _parse_type(self) -> DataType:
        t = self.expect("ident")
        up = t.upper()
        if up in _TYPE_NAMES:
            # swallow optional (n) length params
            if self.eat("punct", "("):
                while not self.eat("punct", ")"):
                    self.next()
            return _TYPE_NAMES[up]()
        if up == "DECIMAL" or up == "NUMERIC":
            prec, scale = 38, 10
            if self.eat("punct", "("):
                prec = int(self.expect("number").value)
                if self.eat("punct", ","):
                    scale = int(self.expect("number").value)
                self.expect("punct", ")")
            return DataType.decimal128(prec, scale)
        if up == "TIMESTAMP":
            return DataType.timestamp("us")
        raise ValueError(f"unknown type {t.value!r}")

    def _parse_function_call(self) -> Expression:
        name_tok = self.next()
        fname = name_tok.upper()
        self.expect("punct", "(")

        if fname == "EXTRACT":
            # EXTRACT(unit FROM expr) — special syntactic form (reference:
            # sqlparser Expr::Extract)
            unit = self.next().upper()
            self.expect_kw("FROM")
            src = self.parse_expr()
            self.expect("punct", ")")
            table = {"YEAR": "year", "MONTH": "month", "DAY": "day",
                     "HOUR": "hour", "MINUTE": "minute", "SECOND": "second",
                     "QUARTER": "quarter", "WEEK": "week_of_year",
                     "DOY": "day_of_year", "DOW": "day_of_week",
                     "MILLISECOND": "millisecond", "MICROSECOND": "microsecond"}
            if unit not in table:
                raise ValueError(f"EXTRACT unit {unit!r} not supported; "
                                 f"known: {sorted(table)}")
            return getattr(src.dt, table[unit])()

        distinct = False
        star = False
        args: List[Expression] = []
        if self.at("op", "*"):
            self.next()
            star = True
        elif not self.at("punct", ")"):
            if self.eat_kw("DISTINCT"):
                distinct = True
            args.append(self.parse_expr())
            while self.eat("punct", ","):
                args.append(self.parse_expr())
        self.expect("punct", ")")

        expr = self._build_function(fname, args, star, distinct)

        # OVER clause → window expression
        if self.at_kw("OVER"):
            self.next()
            spec = self._parse_window_spec()
            from ..expressions.expressions import Alias

            inner, out_name = expr, None
            if isinstance(inner, Alias):
                out_name = inner._alias
                inner = inner.child
            if isinstance(inner, (AggExpr, _UnboundWindowFn)):
                w = inner.over(spec)
                return w.alias(out_name) if out_name else w
            raise ValueError(f"{fname} cannot be used as a window function")
        if isinstance(expr, _UnboundWindowFn):
            raise ValueError(f"{fname}() requires an OVER clause")
        return expr

    def _parse_window_spec(self):
        from ..window import Window

        self.expect("punct", "(")
        w = Window()
        if self.eat_kw("PARTITION"):
            self.expect_kw("BY")
            parts = [self.parse_expr()]
            while self.eat("punct", ","):
                parts.append(self.parse_expr())
            w = w.partition_by(*parts)
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            exprs, descs, nfs = [], [], []
            while True:
                e = self.parse_expr()
                d = False
                if self.eat_kw("DESC"):
                    d = True
                elif self.eat_kw("ASC"):
                    d = False
                nf = None
                if self.eat_kw("NULLS"):
                    if self.eat_kw("FIRST"):
                        nf = True
                    else:
                        self.expect_kw("LAST")
                        nf = False
                exprs.append(e)
                descs.append(d)
                nfs.append(nf if nf is not None else d)
                if not self.eat("punct", ","):
                    break
            w = w.order_by(*exprs, desc=descs, nulls_first=nfs)
        if self.at_kw("ROWS", "RANGE"):
            kind = self.next().upper()
            lo, hi = self._parse_frame_bounds()
            from ..window import Window as W

            if kind == "ROWS":
                w = w.rows_between(lo, hi)
            else:
                w = w.range_between(lo, hi)
        self.expect("punct", ")")
        return w

    def _parse_frame_bounds(self):
        from ..window import Window

        def bound():
            if self.eat_kw("UNBOUNDED"):
                if self.eat_kw("PRECEDING"):
                    return Window.unbounded_preceding
                self.expect_kw("FOLLOWING")
                return Window.unbounded_following
            if self.eat_kw("CURRENT"):
                self.expect_kw("ROW")
                return 0
            n = int(self.expect("number").value)
            if self.eat_kw("PRECEDING"):
                return -n
            self.expect_kw("FOLLOWING")
            return n

        self.expect_kw("BETWEEN")
        lo = bound()
        self.expect_kw("AND")
        hi = bound()
        return lo, hi

    def _build_function(self, fname: str, args: List[Expression], star: bool, distinct: bool) -> Expression:
        from ..functions.registry import has_function
        from .functions import build_sql_function

        if fname in _AGG_FUNCS:
            if fname == "COUNT":
                if star:
                    return AggExpr("count", lit(1), {"mode": "all"}).alias("count")
                if distinct:
                    return AggExpr("count_distinct", args[0])
                return AggExpr("count", args[0], {"mode": "valid"})
            if distinct:
                raise ValueError(f"DISTINCT not supported for {fname}")
            return AggExpr(_AGG_FUNCS[fname], args[0])
        if fname in _WINDOW_RANK_FUNCS:
            params = {"n": int(args[0].value)} if fname == "NTILE" and args else {}
            return _UnboundWindowFn(fname.lower(), None, params)
        if fname in ("LAG", "LEAD"):
            from ..expressions.expressions import Literal

            offset = 1
            default = None
            if len(args) > 1:
                if not isinstance(args[1], Literal):
                    raise ValueError(f"{fname} offset must be a literal integer")
                offset = int(args[1].value)
            if len(args) > 2:
                if not isinstance(args[2], Literal):
                    raise ValueError(f"{fname} default must be a literal")
                default = args[2].value
            return _UnboundWindowFn(fname.lower(), args[0], {"offset": offset, "default": default})
        if fname in ("FIRST_VALUE", "LAST_VALUE"):
            return _UnboundWindowFn(fname.lower(), args[0], {})
        return build_sql_function(fname, args)

    # ---- statements ---------------------------------------------------------------
    def parse_statement(self) -> Select:
        sel = self._parse_select()
        if not self.at("eof") and not self.at("punct", ";"):
            t = self.peek()
            raise ValueError(f"unexpected trailing token {t.value!r} at {t.pos}")
        return sel

    def _parse_select(self) -> Select:
        ctes: Dict[str, Select] = {}
        if self.eat_kw("WITH"):
            while True:
                name = self.expect("ident").value
                self.expect_kw("AS")
                self.expect("punct", "(")
                ctes[name.lower()] = self._parse_select()
                self.expect("punct", ")")
                if not self.eat("punct", ","):
                    break
        sel = self._parse_select_core()
        sel.ctes = ctes
        # set operations
        while True:
            if self.eat_kw("UNION"):
                op = "union_all" if self.eat_kw("ALL") else "union"
                sel.set_ops.append((op, self._parse_select_core()))
            elif self.eat_kw("INTERSECT"):
                sel.set_ops.append(("intersect", self._parse_select_core()))
            elif self.eat_kw("EXCEPT"):
                sel.set_ops.append(("except", self._parse_select_core()))
            else:
                break
        # trailing order/limit apply to the whole compound
        self._parse_order_limit(sel)
        return sel

    def _parse_select_core(self) -> Select:
        self.expect_kw("SELECT")
        sel = Select()
        sel.distinct = self.eat_kw("DISTINCT")
        while True:
            sel.items.append(self._parse_select_item())
            if not self.eat("punct", ","):
                break
        if self.eat_kw("FROM"):
            sel.from_table = self._parse_table_factor()
            while True:
                # SQL-92 comma list = implicit cross join; the optimizer's
                # filter-into-join pushdown recovers the equi-join
                if self.eat("punct", ","):
                    sel.joins.append(JoinClause(self._parse_table_factor(), "cross", None))
                    continue
                j = self._try_parse_join()
                if j is None:
                    break
                sel.joins.append(j)
        if self.eat_kw("WHERE"):
            sel.where = self.parse_expr()
        if self.eat_kw("GROUP"):
            self.expect_kw("BY")
            if self.at_kw("ROLLUP", "CUBE", "GROUPING"):
                sel.grouping_sets = self._parse_grouping_sets()
            else:
                while True:
                    if self.at("number"):
                        sel.group_by.append(int(self.next().value))
                    else:
                        sel.group_by.append(self.parse_expr())
                    if not self.eat("punct", ","):
                        break
        if self.eat_kw("HAVING"):
            sel.having = self.parse_expr()
        return sel

    def _parse_order_limit(self, sel: Select) -> None:
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                if self.at("number"):
                    e = int(self.next().value)
                    item = OrderItem(e)  # position resolved by planner
                else:
                    item = OrderItem(self.parse_expr())
                if self.eat_kw("DESC"):
                    item.desc = True
                else:
                    self.eat_kw("ASC")
                if self.eat_kw("NULLS"):
                    if self.eat_kw("FIRST"):
                        item.nulls_first = True
                    else:
                        self.expect_kw("LAST")
                        item.nulls_first = False
                sel.order_by.append(item)
                if not self.eat("punct", ","):
                    break
        if self.eat_kw("LIMIT"):
            sel.limit = int(self.expect("number").value)
        if self.eat_kw("OFFSET"):
            sel.offset = int(self.expect("number").value)

    def _parse_select_item(self) -> SelectItem:
        if self.at("op", "*"):
            self.next()
            return SelectItem(None, None, wildcard=True)
        # t.* wildcard
        if (self.peek().kind == "ident" and self.peek(1).kind == "punct" and self.peek(1).value == "."
                and self.peek(2).kind == "op" and self.peek(2).value == "*"):
            q = self.next().value
            self.next()
            self.next()
            return SelectItem(None, None, wildcard=True, qualifier=q)
        e = self.parse_expr()
        alias = None
        if self.eat_kw("AS"):
            alias = self.next().value
        elif self.peek().kind == "ident" and self.peek().upper() not in _KEYWORDS_STOP and not self.at("eof"):
            alias = self.next().value
        return SelectItem(e, alias)

    def _parse_table_factor(self) -> TableFactor:
        if self.eat("punct", "("):
            if self.at_kw("VALUES"):
                self.next()
                rows = []
                while True:
                    self.expect("punct", "(")
                    row = [self.parse_expr()]
                    while self.eat("punct", ","):
                        row.append(self.parse_expr())
                    self.expect("punct", ")")
                    rows.append(row)
                    if not self.eat("punct", ","):
                        break
                self.expect("punct", ")")
                alias, col_names = self._parse_alias_with_columns()
                return TableFactor(values=rows, alias=alias, col_names=col_names)
            sub = self._parse_select()
            self.expect("punct", ")")
            alias, col_names = self._parse_alias_with_columns()
            return TableFactor(subquery=sub, alias=alias, col_names=col_names)
        name = self.expect("ident").value
        # dotted table names (catalog.schema.table)
        while self.eat("punct", "."):
            name += "." + self.expect("ident").value
        alias = None
        if self.eat_kw("AS"):
            alias = self.next().value
        elif self.peek().kind == "ident" and self.peek().upper() not in _KEYWORDS_STOP:
            alias = self.next().value
        return TableFactor(name=name, alias=alias)

    def _parse_grouping_sets(self):
        """ROLLUP(a, b) / CUBE(a, b) / GROUPING SETS ((a), (a, b), ()) →
        a list of grouping-key lists (reference: sqlparser GroupByExpr)."""
        kw = self.next().upper()
        if kw == "GROUPING":
            self.expect_kw("SETS")
            self.expect("punct", "(")
            sets = []
            while True:
                self.expect("punct", "(")
                cur = []
                if not self.at("punct", ")"):
                    cur.append(self.parse_expr())
                    while self.eat("punct", ","):
                        cur.append(self.parse_expr())
                self.expect("punct", ")")
                sets.append(cur)
                if not self.eat("punct", ","):
                    break
            self.expect("punct", ")")
            return sets
        self.expect("punct", "(")
        keys = [self.parse_expr()]
        while self.eat("punct", ","):
            keys.append(self.parse_expr())
        self.expect("punct", ")")
        if kw == "ROLLUP":
            return [keys[:i] for i in range(len(keys), -1, -1)]
        if kw == "CUBE":
            import itertools as _it

            sets = []
            for r in range(len(keys), -1, -1):
                for combo in _it.combinations(range(len(keys)), r):
                    sets.append([keys[i] for i in combo])
            return sets
        raise ValueError(f"unexpected grouping keyword {kw}")

    def _parse_alias_with_columns(self):
        """[AS] name [(col, col, ...)] after a parenthesized table factor."""
        alias = None
        col_names = None
        if self.eat_kw("AS"):
            alias = self.next().value
        elif self.peek().kind == "ident" and self.peek().upper() not in _KEYWORDS_STOP:
            alias = self.next().value
        if alias is not None and self.eat("punct", "("):
            col_names = [self.expect("ident").value]
            while self.eat("punct", ","):
                col_names.append(self.expect("ident").value)
            self.expect("punct", ")")
        return alias, col_names

    def _try_parse_join(self) -> Optional[JoinClause]:
        kind = None
        if self.eat_kw("CROSS"):
            self.expect_kw("JOIN")
            kind = "cross"
        elif self.eat_kw("INNER"):
            self.expect_kw("JOIN")
            kind = "inner"
        elif self.at_kw("LEFT", "RIGHT", "FULL"):
            k = self.next().upper()
            self.eat_kw("OUTER")
            if self.eat_kw("SEMI"):
                kind = "semi" if k == "LEFT" else "right_semi"
            elif self.eat_kw("ANTI"):
                kind = "anti" if k == "LEFT" else "right_anti"
            else:
                kind = {"LEFT": "left", "RIGHT": "right", "FULL": "outer"}[k]
            self.expect_kw("JOIN")
        elif self.eat_kw("JOIN"):
            kind = "inner"
        else:
            return None
        factor = self._parse_table_factor()
        on = None
        using = None
        if kind != "cross":
            if self.eat_kw("ON"):
                on = self.parse_expr()
            elif self.eat_kw("USING"):
                self.expect("punct", "(")
                using = [self.expect("ident").value]
                while self.eat("punct", ","):
                    using.append(self.expect("ident").value)
                self.expect("punct", ")")
        return JoinClause(factor, kind, on, using)


def parse_expression(text: str) -> Expression:
    p = Parser(text)
    e = p.parse_expr()
    if not p.at("eof"):
        t = p.peek()
        raise ValueError(f"unexpected trailing token {t.value!r} at {t.pos}")
    return e


def parse_select(text: str) -> Select:
    return Parser(text).parse_statement()
