"""daft_tpu console entry point (reference parity: daft/cli.py + daft-cli).

    python -m daft_tpu info                 # engine/backend/device summary
    python -m daft_tpu sql "SELECT ..."     # run SQL over registered files
    python -m daft_tpu bench                # run the TPC-H benchmark
    python -m daft_tpu schema PATH          # print a file's inferred schema
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_info(_args) -> int:
    import daft_tpu

    print(f"daft_tpu {daft_tpu.__version__}")
    try:
        from .utils import jax_setup  # noqa: F401
        import jax

        print(f"jax {jax.__version__} backend={jax.default_backend()} "
              f"devices={[str(d) for d in jax.devices()]}")
    except Exception as e:  # pragma: no cover
        print(f"jax unavailable: {e}")
    from .config import execution_config

    print(f"execution config: {execution_config()}")
    return 0


def _cmd_sql(args) -> int:
    import daft_tpu

    session_tables = {}
    for spec in args.table or []:
        name, path = spec.split("=", 1)
        if path.endswith((".parquet", ".pq")) or "*" in path:
            session_tables[name] = daft_tpu.read_parquet(path)
        elif path.endswith(".csv"):
            session_tables[name] = daft_tpu.read_csv(path)
        else:
            session_tables[name] = daft_tpu.read_json(path)
    df = daft_tpu.sql(args.query, **session_tables)
    out = df.limit(args.limit).to_pydict() if args.limit else df.to_pydict()
    if args.json:
        print(json.dumps(out, default=str))
    else:
        cols = list(out)
        n = len(out[cols[0]]) if cols else 0
        print(" | ".join(cols))
        for i in range(n):
            print(" | ".join(str(out[c][i]) for c in cols))
    return 0


def _cmd_schema(args) -> int:
    import daft_tpu

    path = args.path
    if path.endswith((".parquet", ".pq")):
        df = daft_tpu.read_parquet(path)
    elif path.endswith((".csv", ".tsv")):
        df = daft_tpu.read_csv(path)
    else:
        df = daft_tpu.read_json(path)
    for f in df.schema:
        print(f"{f.name}: {f.dtype}")
    return 0


def _cmd_bench(_args) -> int:
    import runpy
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    runpy.run_path(os.path.join(root, "bench.py"), run_name="__main__")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="daft_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("info")
    sp = sub.add_parser("sql")
    sp.add_argument("query")
    sp.add_argument("--table", "-t", action="append",
                    help="name=path bindings usable in the query")
    sp.add_argument("--limit", type=int, default=0)
    sp.add_argument("--json", action="store_true")
    sc = sub.add_parser("schema")
    sc.add_argument("path")
    sub.add_parser("bench")
    args = p.parse_args(argv)
    return {"info": _cmd_info, "sql": _cmd_sql, "schema": _cmd_schema,
            "bench": _cmd_bench}[args.cmd](args)


if __name__ == "__main__":
    raise SystemExit(main())
