"""Custom Python data sinks.

Reference parity: daft/io/sink.py — the DataSink ABC behind
write_turbopuffer/clickhouse/bigtable-style connectors: start() once,
write() per micropartition (possibly on workers), finalize() with the
collected write results to produce the commit/result table.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Iterator, List

from ..core.micropartition import MicroPartition
from ..schema import Schema


class WriteResult:
    """What one write() call produced (rows/bytes plus sink-specific payload)."""

    def __init__(self, result: Any = None, rows: int = 0, bytes_written: int = 0):
        self.result = result
        self.rows = rows
        self.bytes_written = bytes_written


class DataSink(ABC):
    @abstractmethod
    def name(self) -> str:
        ...

    @abstractmethod
    def schema(self) -> Schema:
        """Schema of the result table finalize() returns."""
        ...

    def start(self) -> None:
        """Called once before any write()."""

    @abstractmethod
    def write(self, part: MicroPartition) -> WriteResult:
        ...

    @abstractmethod
    def finalize(self, results: List[WriteResult]) -> MicroPartition:
        """Combine write results into the output table (e.g. commit + manifest)."""
        ...


class _SinkWriteInfo:
    """Adapter matching io.writers.WriteInfo's execute_write contract so the
    physical Sink node runs custom sinks through the same executor path."""

    def __init__(self, sink: DataSink):
        self.sink = sink

    def __repr__(self) -> str:
        return f"sink://{self.sink.name()}"

    def result_schema(self) -> Schema:
        return self.sink.schema()

    def execute_write(self, parts: Iterator[MicroPartition], input_schema: Schema):
        self.sink.start()
        results: List[WriteResult] = []
        for part in parts:
            if part.num_rows == 0:
                continue
            results.append(self.sink.write(part))
        out = self.sink.finalize(results)
        yield out.cast_to_schema(self.sink.schema())
