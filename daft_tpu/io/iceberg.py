"""Apache Iceberg table read support (v1 and v2 metadata).

Reference parity: daft/io/iceberg/iceberg_scan.py (IcebergScanOperator:
snapshot -> manifest list -> manifests -> ScanTasks with partition pruning
through Pushdowns) and daft/catalog/__iceberg.py. The reference leans on
pyiceberg; here the spec is implemented directly: table metadata JSON,
Avro manifest lists/manifests (io/avro.py), identity-transform partition
pruning, and parquet data-file scan tasks.

Layout read:
    {table}/metadata/v{N}.metadata.json   (or *.metadata.json; version-hint.text)
    {table}/metadata/snap-*.avro          (manifest list)
    {table}/metadata/*-m*.avro            (manifests)
    {table}/data/...parquet               (data files)

Unsupported (clear errors, not silent wrong answers): delete files
(v2 row-level deletes), non-parquet data files.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from ..datatype import DataType, Field
from ..schema import Schema
from .avro import read_container
from .scan import Pushdowns, ScanOperator, ScanTask

_DEC = re.compile(r"decimal\((\d+),\s*(\d+)\)")
_FIXED = re.compile(r"fixed\[(\d+)\]")


def _icetype_to_dtype(t: Any) -> DataType:
    if isinstance(t, dict):
        kind = t.get("type")
        if kind == "struct":
            return DataType.struct({f["name"]: _icetype_to_dtype(f["type"])
                                    for f in t["fields"]})
        if kind == "list":
            return DataType.list(_icetype_to_dtype(t["element"]))
        if kind == "map":
            return DataType.map(_icetype_to_dtype(t["key"]), _icetype_to_dtype(t["value"]))
        raise NotImplementedError(f"iceberg type {t!r}")
    m = _DEC.match(t)
    if m:
        return DataType.decimal128(int(m.group(1)), int(m.group(2)))
    m = _FIXED.match(t)
    if m:
        return DataType.fixed_size_binary(int(m.group(1)))
    simple = {
        "boolean": DataType.bool, "int": DataType.int32, "long": DataType.int64,
        "float": DataType.float32, "double": DataType.float64,
        "string": DataType.string, "binary": DataType.binary,
        "date": DataType.date, "uuid": DataType.string,
    }
    if t in simple:
        return simple[t]()
    if t in ("timestamp", "timestamptz"):
        return DataType.timestamp("us", "UTC" if t == "timestamptz" else None)
    if t == "time":
        return DataType.time("us")
    raise NotImplementedError(f"iceberg type {t!r}")


def _load_table_metadata(table_path: str) -> dict:
    mdir = os.path.join(table_path, "metadata")
    if not os.path.isdir(mdir):
        raise FileNotFoundError(f"not an iceberg table (no metadata/): {table_path}")
    hint = os.path.join(mdir, "version-hint.text")
    candidates = [n for n in os.listdir(mdir) if n.endswith(".metadata.json")]
    if not candidates:
        raise FileNotFoundError(f"no *.metadata.json under {mdir}")
    if os.path.exists(hint):
        with open(hint) as f:
            v = f.read().strip()
        for pat in (f"v{v}.metadata.json", f"{v}.metadata.json"):
            if pat in candidates:
                candidates = [pat]
                break
    # highest version wins (vN.metadata.json or NNNNN-uuid.metadata.json)
    def key(n: str):
        m = re.match(r"v?(\d+)", n)
        return int(m.group(1)) if m else -1

    name = sorted(candidates, key=key)[-1]
    with open(os.path.join(mdir, name)) as f:
        return json.load(f)


def _current_schema(meta: dict) -> Tuple[Schema, Dict[int, str]]:
    """(schema, field_id -> name) for the current schema."""
    if "schemas" in meta:
        sid = meta.get("current-schema-id", 0)
        raw = next(s for s in meta["schemas"] if s.get("schema-id", 0) == sid)
    else:
        raw = meta["schema"]
    fields = []
    by_id: Dict[int, str] = {}
    for f in raw["fields"]:
        fields.append(Field(f["name"], _icetype_to_dtype(f["type"])))
        by_id[f["id"]] = f["name"]
    return Schema(fields), by_id


def _partition_spec(meta: dict) -> List[dict]:
    """Current partition spec fields: [{name, transform, source-id}]."""
    if "partition-specs" in meta:
        sid = meta.get("default-spec-id", 0)
        spec = next(s for s in meta["partition-specs"] if s.get("spec-id", 0) == sid)
        return spec.get("fields", [])
    return meta.get("partition-spec", [])


def _resolve_path(table_path: str, location: str, file_path: str) -> str:
    """Manifest/data paths are absolute URIs from the writer's view; re-anchor
    them under the local table directory so relocated tables still read."""
    if os.path.exists(file_path):
        return file_path
    p = file_path
    for scheme in ("file://", "s3://", "gs://", "abfs://"):
        if p.startswith(scheme):
            p = p[len(scheme):]
            break
    if location:
        loc = location.rstrip("/")
        for scheme in ("file://", "s3://", "gs://", "abfs://"):
            if loc.startswith(scheme):
                loc = loc[len(scheme):]
                break
        if p.startswith(loc + "/"):
            return os.path.join(table_path, p[len(loc) + 1:])
    # last resort: anchor at the path component after the table dir name
    base = os.path.basename(os.path.normpath(table_path))
    idx = p.find("/" + base + "/")
    if idx >= 0:
        return os.path.join(table_path, p[idx + len(base) + 2:])
    return p


class IcebergScanOperator(ScanOperator):
    def __init__(self, table_path: str, snapshot_id: Optional[int] = None,
                 meta: Optional[dict] = None):
        """`meta` preloads the table metadata (REST catalogs hand it over the
        wire — daft_tpu/io/iceberg_rest.py); otherwise it is resolved from
        {table_path}/metadata via version-hint."""
        self.table_path = table_path
        self.meta = meta if meta is not None else _load_table_metadata(table_path)
        self._schema, self._field_names = _current_schema(self.meta)
        self._spec = _partition_spec(self.meta)
        self._snapshot = self._pick_snapshot(snapshot_id)
        self._data_files_cache: Optional[List[dict]] = None

    def _pick_snapshot(self, snapshot_id: Optional[int]) -> Optional[dict]:
        snaps = self.meta.get("snapshots") or []
        if snapshot_id is not None:
            for s in snaps:
                if s["snapshot-id"] == snapshot_id:
                    return s
            raise ValueError(f"snapshot {snapshot_id} not found")
        cur = self.meta.get("current-snapshot-id")
        for s in snaps:
            if s["snapshot-id"] == cur:
                return s
        return snaps[-1] if snaps else None

    def name(self) -> str:
        return f"IcebergScan({os.path.basename(os.path.normpath(self.table_path))})"

    def schema(self) -> Schema:
        return self._schema

    def can_absorb_select(self) -> bool:
        return True

    def can_absorb_filter(self) -> bool:
        return True

    def can_absorb_limit(self) -> bool:
        return True

    # ---- manifests ---------------------------------------------------------------
    def _data_files(self) -> List[dict]:
        """Walk snapshot -> manifest list -> manifests -> live data files.
        Memoized: metadata is immutable for a pinned snapshot, and the
        optimizer calls this via both approx_num_rows and to_scan_tasks."""
        if self._data_files_cache is not None:
            return self._data_files_cache
        if self._snapshot is None:
            return []
        loc = self.meta.get("location", "")
        out: List[dict] = []
        manifests: List[dict] = []
        if "manifest-list" in self._snapshot:
            ml_path = _resolve_path(self.table_path, loc, self._snapshot["manifest-list"])
            _s, manifests = read_container(open(ml_path, "rb").read())
        else:  # v1 inline manifest array
            manifests = [{"manifest_path": p, "content": 0}
                         for p in self._snapshot.get("manifests", [])]
        for m in manifests:
            if m.get("content", 0) == 1:
                raise NotImplementedError(
                    "iceberg delete manifests (v2 row-level deletes) are not supported")
            mp = _resolve_path(self.table_path, loc, m["manifest_path"])
            _s, entries = read_container(open(mp, "rb").read())
            for e in entries:
                if e.get("status", 1) == 2:  # DELETED
                    continue
                df = e["data_file"]
                if df.get("content", 0) != 0:
                    raise NotImplementedError("iceberg delete files are not supported")
                fmt = (df.get("file_format") or "PARQUET").upper()
                if fmt != "PARQUET":
                    raise NotImplementedError(f"iceberg data file format {fmt}")
                out.append(df)
        self._data_files_cache = out
        return out

    # ---- partition pruning -------------------------------------------------------
    def _identity_partition_values(self, df: dict) -> Dict[str, Any]:
        """column name -> partition value for identity-transform spec fields."""
        part = df.get("partition") or {}
        vals: Dict[str, Any] = {}
        for f in self._spec:
            if f.get("transform") != "identity":
                continue
            src = self._field_names.get(f.get("source-id"))
            if src is None:
                continue
            # manifest partition record field is named after the spec field
            if f["name"] in part:
                vals[src] = part[f["name"]]
        return vals

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        from .parquet import _expr_to_arrow_filter, _zone_map_conjuncts

        schema = self._schema
        columns = pushdowns.columns
        out_schema = Schema([schema[c] for c in columns]) if columns is not None else schema
        conjuncts = _zone_map_conjuncts(pushdowns.filters) \
            if pushdowns.filters is not None else []
        arrow_filter = _expr_to_arrow_filter(pushdowns.filters) \
            if pushdowns.filters is not None else None
        loc = self.meta.get("location", "")

        tasks: List[ScanTask] = []
        for df in self._data_files():
            pvals = self._identity_partition_values(df)
            if pvals and conjuncts and _pruned_by_partition(pvals, conjuncts):
                continue
            path = _resolve_path(self.table_path, loc, df["file_path"])
            tasks.append(_parquet_task(path, columns, arrow_filter, out_schema,
                                       df.get("file_size_in_bytes"),
                                       df.get("record_count")))
        return tasks

    def approx_num_rows(self, pushdowns: Pushdowns) -> Optional[float]:
        try:
            total = sum(int(df.get("record_count") or 0) for df in self._data_files())
        except NotImplementedError:
            return None
        if pushdowns.limit is not None:
            total = min(total, pushdowns.limit)
        return float(total)


def _pruned_by_partition(pvals: Dict[str, Any], conjuncts: List[tuple]) -> bool:
    """True when some pushed conjunct (col, op, value) proves this file's
    identity partition can contain no matching row."""
    for colname, op, val in conjuncts:
        if colname not in pvals:
            continue
        pv = pvals[colname]
        if pv is None:
            continue
        try:
            if op == "eq" and not (pv == val):
                return True
            if op == "lt" and not (pv < val):
                return True
            if op == "le" and not (pv <= val):
                return True
            if op == "gt" and not (pv > val):
                return True
            if op == "ge" and not (pv >= val):
                return True
        except TypeError:
            continue
    return False


def _parquet_task(path: str, columns, arrow_filter, out_schema: Schema,
                  size_bytes: Optional[int], num_rows: Optional[int]) -> ScanTask:
    def read():
        import pyarrow.parquet as pq

        from ..core.micropartition import MicroPartition
        from ..core.recordbatch import RecordBatch

        table = pq.read_table(path, columns=columns, filters=arrow_filter)
        batch = RecordBatch.from_arrow(table).cast_to_schema(out_schema)
        yield MicroPartition(out_schema, [batch])

    return ScanTask(read=read, schema=out_schema, size_bytes=size_bytes,
                    num_rows=num_rows, filters_applied=arrow_filter is not None,
                    limit_applied=False, source_label=path)


# ======================================================================================
# Write path
# ======================================================================================


def _dtype_to_icetype(dt: DataType) -> Any:
    if dt.is_struct():
        return {"type": "struct",
                "fields": [{"id": 1000 + i, "name": n, "required": False,
                            "type": _dtype_to_icetype(t)}
                           for i, (n, t) in enumerate(dt.struct_fields)]}
    if dt.is_list():
        return {"type": "list", "element-id": 1100, "element-required": False,
                "element": _dtype_to_icetype(dt.inner)}
    if dt.is_decimal():
        p, s = dt.params
        return f"decimal({p},{s})"
    simple = {
        DataType.bool(): "boolean", DataType.int32(): "int",
        DataType.int64(): "long", DataType.float32(): "float",
        DataType.float64(): "double", DataType.string(): "string",
        DataType.binary(): "binary", DataType.date(): "date",
    }
    if dt in simple:
        return simple[dt]
    if dt.kind == "timestamp":
        return "timestamptz" if len(dt.params) > 1 and dt.params[1] else "timestamp"
    if dt.is_integer():
        return "long"
    raise NotImplementedError(f"cannot map {dt} to an iceberg type")


def _ice_avro_partition_fields(schema: Schema, partition_cols: List[str]):
    """Avro record fields for the manifest partition tuple (identity spec)."""
    amap = {"int64": "long", "int32": "int", "string": "string", "bool": "boolean",
            "float64": "double", "float32": "float", "date": "int"}
    out = []
    for i, name in enumerate(partition_cols):
        kind = schema[name].dtype.kind
        at = amap.get(kind, "long" if schema[name].dtype.is_integer() else "string")
        out.append({"name": name, "type": ["null", at], "default": None,
                    "field-id": 1000 + i})
    return out


def write_iceberg(df, table_path: str, mode: str = "append",
                  partition_cols: Optional[List[str]] = None):
    """Write a DataFrame as an Iceberg v2 table (reference:
    DataFrame.write_iceberg via pyiceberg; here the spec is emitted directly —
    parquet data files, Avro manifest + manifest list, table metadata JSON —
    in the same layout read_iceberg() and pyiceberg parse).

    mode: "append" | "overwrite" | "error" | "ignore".
    Partitioning: identity transforms over partition_cols.
    """
    import time as _time
    import uuid as _uuid

    import pyarrow as pa
    import pyarrow.compute as pc_
    import pyarrow.parquet as pq

    from .. import api as _api
    from .avro import write_container

    meta_dir = os.path.join(table_path, "metadata")
    data_dir = os.path.join(table_path, "data")
    exists = os.path.isdir(meta_dir) and any(
        n.endswith(".metadata.json") for n in os.listdir(meta_dir)) \
        if os.path.isdir(meta_dir) else False
    if exists and mode == "error":
        raise FileExistsError(f"iceberg table already exists: {table_path}")
    if exists and mode == "ignore":
        return _api.from_pydict({"path": [], "rows": []})
    os.makedirs(meta_dir, exist_ok=True)
    os.makedirs(data_dir, exist_ok=True)

    schema = df.schema
    parts = list(partition_cols or [])
    for p in parts:
        if p not in schema.column_names():
            raise ValueError(f"partition column {p!r} not in schema")

    now_ms = int(_time.time() * 1000)
    snapshot_id = now_ms * 1000 + int.from_bytes(os.urandom(2), "little") % 1000

    # prior state (append keeps old manifests; overwrite drops them)
    version = 0
    prior_manifests: List[dict] = []
    prior_meta: Optional[dict] = None
    if exists:
        prior_meta = _load_table_metadata(table_path)
        version = int(prior_meta.get("_version", 0)) + 1 \
            if "_version" in prior_meta else _next_metadata_version(meta_dir)
        if mode == "append":
            cur = next((s for s in prior_meta.get("snapshots", [])
                        if s.get("snapshot-id") == prior_meta.get("current-snapshot-id")),
                       None)
            if cur and "manifest-list" in cur:
                ml = _resolve_path(table_path, prior_meta.get("location", ""),
                                   cur["manifest-list"])
                _s, prior_manifests = read_container(open(ml, "rb").read())
    else:
        version = 1

    # ---- data files ----------------------------------------------------------------
    table = df.to_arrow()
    files: List[dict] = []  # (path, rows, size, partition record)

    def _write_file(tbl, pvals: Dict[str, Any]) -> None:
        # partition columns stay IN the data files (like pyiceberg's writer);
        # the partition record exists for manifest-level pruning only
        fname = f"{_uuid.uuid4().hex}.parquet"
        fpath = os.path.join(data_dir, fname)
        pq.write_table(tbl, fpath)
        files.append({"path": f"{table_path}/data/{fname}", "rows": tbl.num_rows,
                      "size": os.path.getsize(fpath), "partition": pvals})

    if not parts:
        _write_file(table, {})
    else:
        combos = table.group_by(parts).aggregate([]).to_pylist()
        for row in combos:
            mask = None
            for p in parts:
                m = pc_.equal(table.column(p), pa.scalar(row[p])) \
                    if row[p] is not None else pc_.is_null(table.column(p))
                mask = m if mask is None else pc_.and_(mask, m)
            _write_file(table.filter(mask), {p: row[p] for p in parts})

    # ---- manifest (avro) -----------------------------------------------------------
    part_fields = _ice_avro_partition_fields(schema, parts)
    # field-id attributes follow the Iceberg spec's manifest field IDs —
    # external readers (pyiceberg/Spark/Trino) resolve manifest columns by
    # field-id, not name (spec: "Manifests", table "manifest_entry fields")
    data_file_schema = {
        "type": "record", "name": "r2", "fields": [
            {"name": "content", "type": "int", "field-id": 134},
            {"name": "file_path", "type": "string", "field-id": 100},
            {"name": "file_format", "type": "string", "field-id": 101},
            {"name": "partition",
             "type": {"type": "record", "name": "r102", "fields": part_fields},
             "field-id": 102},
            {"name": "record_count", "type": "long", "field-id": 103},
            {"name": "file_size_in_bytes", "type": "long", "field-id": 104},
        ]}
    entry_schema = {
        "type": "record", "name": "manifest_entry", "fields": [
            {"name": "status", "type": "int", "field-id": 0},
            {"name": "snapshot_id", "type": ["null", "long"], "default": None,
             "field-id": 1},
            {"name": "data_file", "type": data_file_schema, "field-id": 2},
        ]}
    manifest_name = f"{_uuid.uuid4().hex}-m0.avro"
    manifest_path = os.path.join(meta_dir, manifest_name)
    entries = [{"status": 1, "snapshot_id": snapshot_id,
                "data_file": {"content": 0, "file_path": f["path"],
                              "file_format": "PARQUET",
                              "partition": f["partition"],
                              "record_count": f["rows"],
                              "file_size_in_bytes": f["size"]}}
               for f in files]
    write_container(manifest_path, entry_schema, entries)

    # ---- manifest list (avro) --------------------------------------------------------
    ml_schema = {
        "type": "record", "name": "manifest_file", "fields": [
            {"name": "manifest_path", "type": "string", "field-id": 500},
            {"name": "manifest_length", "type": "long", "field-id": 501},
            {"name": "partition_spec_id", "type": "int", "field-id": 502},
            {"name": "content", "type": "int", "field-id": 517},
            {"name": "added_snapshot_id", "type": "long", "field-id": 503},
        ]}
    ml_records = [{"manifest_path": f"{table_path}/metadata/{manifest_name}",
                   "manifest_length": os.path.getsize(manifest_path),
                   "partition_spec_id": 0, "content": 0,
                   "added_snapshot_id": snapshot_id}]
    for pm in prior_manifests:
        ml_records.append({
            "manifest_path": pm["manifest_path"],
            "manifest_length": pm.get("manifest_length", 0),
            "partition_spec_id": pm.get("partition_spec_id", 0),
            "content": pm.get("content", 0),
            "added_snapshot_id": pm.get("added_snapshot_id", snapshot_id)})
    ml_name = f"snap-{snapshot_id}-1-{_uuid.uuid4().hex}.avro"
    write_container(os.path.join(meta_dir, ml_name), ml_schema, ml_records)

    # ---- table metadata json ---------------------------------------------------------
    fields = [{"id": i + 1, "name": f.name, "required": False,
               "type": _dtype_to_icetype(f.dtype)}
              for i, f in enumerate(schema)]
    name_to_id = {f["name"]: f["id"] for f in fields}
    spec_fields = [{"name": p, "transform": "identity",
                    "source-id": name_to_id[p], "field-id": 1000 + i}
                   for i, p in enumerate(parts)]
    snapshots = []
    if prior_meta is not None and mode == "append":
        snapshots = list(prior_meta.get("snapshots", []))
    snapshots.append({"snapshot-id": snapshot_id, "timestamp-ms": now_ms,
                      "manifest-list": f"{table_path}/metadata/{ml_name}",
                      "summary": {"operation": "append" if mode == "append"
                                  else "overwrite"},
                      "schema-id": 0})
    meta = {
        "format-version": 2,
        "table-uuid": str(_uuid.uuid4()) if prior_meta is None
        else prior_meta.get("table-uuid", str(_uuid.uuid4())),
        "location": table_path,
        "last-sequence-number": len(snapshots),
        "last-updated-ms": now_ms,
        "last-column-id": len(fields),
        "schemas": [{"type": "struct", "schema-id": 0, "fields": fields}],
        "current-schema-id": 0,
        "partition-specs": [{"spec-id": 0, "fields": spec_fields}],
        "default-spec-id": 0,
        "last-partition-id": 1000 + len(spec_fields),
        "sort-orders": [{"order-id": 0, "fields": []}],
        "default-sort-order-id": 0,
        "properties": {},
        "current-snapshot-id": snapshot_id,
        "snapshots": snapshots,
        "snapshot-log": [], "metadata-log": [],
    }
    with open(os.path.join(meta_dir, f"v{version}.metadata.json"), "w") as f:
        json.dump(meta, f)
    with open(os.path.join(meta_dir, "version-hint.text"), "w") as f:
        f.write(str(version))

    return _api.from_pydict({"path": [f["path"] for f in files],
                             "rows": [f["rows"] for f in files]})


def _next_metadata_version(meta_dir: str) -> int:
    best = 0
    for n in os.listdir(meta_dir):
        m = re.match(r"v(\d+)\.metadata\.json$", n)
        if m:
            best = max(best, int(m.group(1)))
    return best + 1
