"""from_glob_path: a DataFrame of file metadata (reference: daft.from_glob_path)."""

from __future__ import annotations

import os
from typing import List

from ..core.micropartition import MicroPartition
from ..datatype import DataType, Field
from ..schema import Schema
from .paths import expand_paths
from .scan import Pushdowns, ScanOperator, ScanTask


class GlobPathScanOperator(ScanOperator):
    def __init__(self, pattern: str):
        self._pattern = pattern
        self._out_schema = Schema([
            Field("path", DataType.string()),
            Field("size", DataType.int64()),
            Field("num_rows", DataType.int64()),
        ])

    def name(self) -> str:
        return f"GlobPathScan({self._pattern})"

    def schema(self) -> Schema:
        return self._out_schema

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        schema = self.schema()

        def read():
            paths = expand_paths(self._pattern)
            sizes = [os.path.getsize(p) if os.path.exists(p) else None for p in paths]
            yield MicroPartition.from_pydict({
                "path": paths,
                "size": sizes,
                "num_rows": [None] * len(paths),
            }).cast_to_schema(schema)

        return [ScanTask(read=read, schema=schema, source_label=self._pattern)]
