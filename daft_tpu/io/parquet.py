"""Parquet scan operator.

Reference parity: src/daft-parquet/src/read.rs:440,490 (bulk + streaming reads,
row-group pruning via statistics) and src/daft-scan/src/glob.rs. Host-side IO is
pyarrow-backed; tasks split per file (and per row-group for large files) so the
executor can parallelize and the optimizer's pushdowns (columns/filters/limit)
prune IO before any byte is read.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Union

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from ..core.micropartition import MicroPartition
from ..schema import Schema
from .paths import expand_paths
from .scan import Pushdowns, ScanOperator, ScanTask

# target rows per emitted MicroPartition batch chunk
_MORSEL_ROWS = 128 * 1024


class ParquetScanOperator(ScanOperator):
    def __init__(self, path: Union[str, List[str]], schema: Optional[Schema] = None,
                 row_groups_per_task: Optional[int] = None, **_options):
        self._paths = expand_paths(path, (".parquet", ".pq"))
        if not self._paths:
            raise FileNotFoundError(f"no parquet files matched {path!r}")
        self._schema = schema
        self._row_groups_per_task = row_groups_per_task

    def name(self) -> str:
        return f"ParquetScan({len(self._paths)} files)"

    def schema(self) -> Schema:
        if self._schema is None:
            from .object_store import open_input

            # schema inference from the first file (reference: schema_inference.rs);
            # remote objects read only the footer via ranged reads
            self._schema = Schema.from_arrow(pq.read_schema(open_input(self._paths[0])))
        return self._schema

    def can_absorb_select(self) -> bool:
        return True

    def can_absorb_filter(self) -> bool:
        return True

    def can_absorb_limit(self) -> bool:
        return True

    def approx_num_rows(self, pushdowns: Pushdowns) -> Optional[float]:
        from .object_store import open_input

        total = 0
        for p in self._paths:
            try:
                total += pq.ParquetFile(open_input(p)).metadata.num_rows
            except Exception:
                return None
        if pushdowns.limit is not None:
            total = min(total, pushdowns.limit)
        return float(total)

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        schema = self.schema()
        columns = pushdowns.columns
        out_schema = Schema([schema[c] for c in columns]) if columns is not None else schema
        arrow_filter = _expr_to_arrow_filter(pushdowns.filters) if pushdowns.filters is not None else None

        from .object_store import is_remote

        tasks = []
        for path in self._paths:
            tasks.append(ScanTask(
                read=_make_reader(path, columns, arrow_filter, pushdowns.limit, out_schema),
                schema=out_schema,
                size_bytes=os.path.getsize(path) if os.path.exists(path) else None,
                # remote readers don't evaluate the predicate; the executor
                # re-applies it post-scan
                filters_applied=arrow_filter is not None and not is_remote(path),
                limit_applied=False,
                source_label=path,
            ))
        return tasks


def _make_reader(path: str, columns, arrow_filter, limit, out_schema: Schema):
    from .object_store import is_remote

    if is_remote(path):
        def read_remote():
            from .object_store import open_input

            # ranged-read file: column pruning downloads only touched byte
            # ranges; predicate re-applied by the executor (filters_applied is
            # False for remote tasks)
            pf = pq.ParquetFile(open_input(path))
            produced = 0
            for rb in pf.iter_batches(batch_size=_MORSEL_ROWS, columns=columns):
                if limit is not None and produced >= limit:
                    return
                t = pa.Table.from_batches([rb])
                if limit is not None and produced + t.num_rows > limit:
                    t = t.slice(0, limit - produced)
                produced += t.num_rows
                yield MicroPartition.from_arrow(t).cast_to_schema(out_schema)

        return read_remote

    def read():
        ds = pads.dataset(path, format="parquet")
        scanner = ds.scanner(columns=columns, filter=arrow_filter, batch_size=_MORSEL_ROWS)
        produced = 0
        for rb in scanner.to_batches():
            if limit is not None and produced >= limit:
                return
            t = pa.Table.from_batches([rb])
            if limit is not None and produced + t.num_rows > limit:
                t = t.slice(0, limit - produced)
            produced += t.num_rows
            mp = MicroPartition.from_arrow(t)
            yield mp.cast_to_schema(out_schema)

    return read


def _expr_to_arrow_filter(expr) -> Optional[pads.Expression]:
    """Best-effort translation of our Expression IR to a pyarrow dataset filter.
    Returns None when any node has no arrow equivalent (filter then re-applied
    post-scan by the executor; translate() checks filters_applied)."""
    import pyarrow.compute as pc

    from ..expressions import Between, BinaryOp, ColumnRef, IsIn, Literal, UnaryOp

    def conv(e):
        if isinstance(e, ColumnRef):
            return pads.field(e._name)
        if isinstance(e, Literal):
            return pa.scalar(e.value)
        if isinstance(e, BinaryOp):
            l, r = conv(e.left), conv(e.right)
            if l is None or r is None:
                return None
            ops = {
                "add": lambda a, b: a + b, "sub": lambda a, b: a - b,
                "mul": lambda a, b: a * b, "div": lambda a, b: a / b,
                "eq": lambda a, b: a == b, "neq": lambda a, b: a != b,
                "lt": lambda a, b: a < b, "le": lambda a, b: a <= b,
                "gt": lambda a, b: a > b, "ge": lambda a, b: a >= b,
                "and": lambda a, b: a & b, "or": lambda a, b: a | b,
            }
            f = ops.get(e.op)
            return f(l, r) if f else None
        if isinstance(e, UnaryOp):
            c = conv(e.child)
            if c is None:
                return None
            if e.op == "not":
                return ~c
            if e.op == "is_null":
                return c.is_null()
            if e.op == "not_null":
                return c.is_valid()
            return None
        if isinstance(e, Between):
            c, lo, hi = conv(e.child), conv(e.lower), conv(e.upper)
            if c is None or lo is None or hi is None:
                return None
            return (c >= lo) & (c <= hi)
        if isinstance(e, IsIn):
            c = conv(e.child)
            vals = []
            for item in e.items:
                if not isinstance(item, Literal):
                    return None
                vals.append(item.value)
            return c.isin(vals) if c is not None else None
        return None

    try:
        return conv(expr)
    except Exception:
        return None
