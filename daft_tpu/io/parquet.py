"""Parquet scan operator.

Reference parity: src/daft-parquet/src/read.rs:440,490 (bulk + streaming reads,
row-group pruning via statistics) and src/daft-scan/src/glob.rs. Host-side IO is
pyarrow-backed; tasks split per file (and per row-group for large files) so the
executor can parallelize and the optimizer's pushdowns (columns/filters/limit)
prune IO before any byte is read.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Union

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from ..core.micropartition import MicroPartition
from ..schema import Schema
from .paths import expand_paths
from .scan import Pushdowns, ScanOperator, ScanTask

def _scan_batch_rows() -> int:
    """Target rows per emitted MicroPartition batch chunk — the config's
    morsel_size_rows (read at READ time, not plan time, so DAFT_TPU_MORSEL_SIZE
    and batching-strategy resizes reach scan-fed pipelines). Was a hardcoded
    128Ki that silently ignored the knob (PR 4 unified the executor's
    partial-agg splitter; this closes the scan side)."""
    from ..config import execution_config

    return max(execution_config().morsel_size_rows, 1)


class ParquetScanOperator(ScanOperator):
    def __init__(self, path: Union[str, List[str]], schema: Optional[Schema] = None,
                 row_groups_per_task: Optional[int] = None, **_options):
        self._paths = expand_paths(path, (".parquet", ".pq"))
        if not self._paths:
            raise FileNotFoundError(f"no parquet files matched {path!r}")
        self._schema = schema
        self._row_groups_per_task = row_groups_per_task

    def name(self) -> str:
        return f"ParquetScan({len(self._paths)} files)"

    def schema(self) -> Schema:
        if self._schema is None:
            from .object_store import open_input

            # schema inference from the first file (reference: schema_inference.rs);
            # remote objects read only the footer via ranged reads
            self._schema = Schema.from_arrow(pq.read_schema(open_input(self._paths[0])))
        return self._schema

    def can_absorb_select(self) -> bool:
        return True

    def can_absorb_filter(self) -> bool:
        return True

    def can_absorb_limit(self) -> bool:
        return True

    def approx_num_rows(self, pushdowns: Pushdowns) -> Optional[float]:
        from .object_store import open_input

        total = 0
        for p in self._paths:
            try:
                total += pq.ParquetFile(open_input(p)).metadata.num_rows
            except Exception:  # lint: ignore[broad-except] -- row estimate is advisory
                return None
        if pushdowns.limit is not None:
            total = min(total, pushdowns.limit)
        return float(total)

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        schema = self.schema()
        columns = pushdowns.columns
        out_schema = Schema([schema[c] for c in columns]) if columns is not None else schema
        arrow_filter = _expr_to_arrow_filter(pushdowns.filters) if pushdowns.filters is not None else None

        from ..config import execution_config
        from .object_store import is_remote

        split_bytes = execution_config().scan_split_bytes
        tasks = []
        conjuncts = _zone_map_conjuncts(pushdowns.filters) if pushdowns.filters is not None else []
        for path in self._paths:
            remote = is_remote(path)
            size = os.path.getsize(path) if os.path.exists(path) else None
            want_split = (not remote and size is not None
                          and (self._row_groups_per_task is not None
                               or (split_bytes and size > split_bytes)))
            # one footer parse per local file serves BOTH zone-map pruning
            # and split planning (a filtered many-file scan used to pay two)
            md = _local_metadata(path) if not remote and (conjuncts or want_split) \
                else None
            if conjuncts:
                if md is not None:
                    if _prunable_md(md, conjuncts):
                        continue  # zone map proved no row can match
                elif remote and _file_prunable(path, conjuncts):
                    continue  # same proof via ranged footer reads
            if want_split and md is not None:
                split = _row_group_split_tasks(
                    path, md, columns, out_schema, conjuncts,
                    split_bytes or size, self._row_groups_per_task)
                if split is not None:
                    tasks.extend(split)
                    continue
            tasks.append(ScanTask(
                read=_make_reader(path, columns, arrow_filter, pushdowns.limit, out_schema),
                schema=out_schema,
                size_bytes=size,
                # remote readers don't evaluate the predicate; the executor
                # re-applies it post-scan
                filters_applied=arrow_filter is not None and not is_remote(path),
                limit_applied=False,
                source_label=path,
            ))
        return tasks


def _zone_map_conjuncts(expr) -> List[tuple]:
    """Extract (column, op, literal) constraints usable against row-group
    min/max statistics (reference: daft-parquet statistics/ + daft-stats
    zone-map pruning). Only top-level AND conjuncts of simple comparisons."""
    from ..expressions import Between, BinaryOp, ColumnRef, Literal

    out = []

    def walk(e):
        if isinstance(e, BinaryOp) and e.op == "and":
            walk(e.left)
            walk(e.right)
            return
        if isinstance(e, BinaryOp) and e.op in ("lt", "le", "gt", "ge", "eq"):
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
            if isinstance(e.left, ColumnRef) and isinstance(e.right, Literal):
                out.append((e.left._name, e.op, e.right.value))
            elif isinstance(e.right, ColumnRef) and isinstance(e.left, Literal):
                out.append((e.right._name, flip[e.op], e.left.value))
            return
        if isinstance(e, Between) and isinstance(e.child, ColumnRef):
            if isinstance(e.lower, Literal) and isinstance(e.upper, Literal):
                out.append((e.child._name, "ge", e.lower.value))
                out.append((e.child._name, "le", e.upper.value))

    walk(expr)
    return out


def _rg_excluded(rg, conjuncts: List[tuple]) -> bool:
    """True iff row-group statistics PROVE no row in `rg` satisfies some
    conjunct (shared by file-level pruning and split planning)."""
    cols = {rg.column(i).path_in_schema: rg.column(i).statistics
            for i in range(rg.num_columns)}
    for name, op, value in conjuncts:
        st = cols.get(name)
        if st is None or not st.has_min_max:
            continue
        try:
            if op == "lt" and not (st.min < value):
                return True
            if op == "le" and not (st.min <= value):
                return True
            if op == "gt" and not (st.max > value):
                return True
            if op == "ge" and not (st.max >= value):
                return True
            if op == "eq" and not (st.min <= value <= st.max):
                return True
        except TypeError:
            continue  # incomparable stats (e.g. logical-type mismatch)
    return False


def _local_metadata(path: str):
    """Parsed footer metadata of a LOCAL parquet file, or None when the
    footer is unreadable (callers degrade to whole-file/no-prune planning)."""
    try:
        return pq.ParquetFile(path).metadata
    except Exception:  # lint: ignore[broad-except] -- unreadable footer: plan without metadata
        return None


def _prunable_md(md, conjuncts: List[tuple]) -> bool:
    """True iff the statistics in `md` PROVE no row satisfies the predicate
    — every row group must be excluded by some conjunct."""
    for rg_i in range(md.num_row_groups):
        if not _rg_excluded(md.row_group(rg_i), conjuncts):
            return False  # this row group might match
    return md.num_row_groups > 0


def _file_prunable(path: str, conjuncts: List[tuple]) -> bool:
    """Remote-object variant of _prunable_md: reads just the footer via
    ranged gets; never prunes on metadata trouble."""
    from .object_store import open_input

    try:
        return _prunable_md(pq.ParquetFile(open_input(path)).metadata, conjuncts)
    except Exception:  # lint: ignore[broad-except] -- never prune on metadata trouble
        return False


def _row_group_split_tasks(path: str, md, columns, out_schema: Schema,
                           conjuncts: List[tuple], split_bytes: int,
                           row_groups_per_task: Optional[int]) -> Optional[List[ScanTask]]:
    """Split one large local parquet file into row-group-aligned ScanTasks
    so no single scan task materializes more than ~split_bytes (reference:
    daft-scan's ScanTask-per-row-group splitting). `md` is the caller's
    already-parsed footer metadata. Row groups a zone-map conjunct excludes
    are dropped at plan time. Returns None when the file can't split (one
    row group, everything pruned into one task) — the caller falls back to
    the whole-file task.

    Split tasks read via ``ParquetFile.iter_batches(row_groups=...)`` with
    column pruning but WITHOUT the arrow predicate (``filters_applied`` is
    False, so the executor re-applies the pushed filter post-scan — exactly
    the remote-reader contract)."""
    if md.num_row_groups <= 1:
        return None
    groups: List[List[int]] = []
    sizes: List[int] = []
    rows: List[int] = []
    cur: List[int] = []
    cur_bytes = cur_rows = 0
    for rg_i in range(md.num_row_groups):
        rg = md.row_group(rg_i)
        if conjuncts and _rg_excluded(rg, conjuncts):
            continue  # zone map: no row in this group can match
        # ON-DISK bytes (compressed), not rg.total_byte_size (uncompressed):
        # whole-file tasks report file size, and planner byte estimates /
        # task merging must see one unit, or the same table looks several
        # times bigger once split (flipping broadcast-join eligibility)
        nb = sum(rg.column(ci).total_compressed_size
                 for ci in range(rg.num_columns))
        if cur and (cur_bytes + nb > split_bytes
                    or (row_groups_per_task is not None
                        and len(cur) >= row_groups_per_task)):
            groups.append(cur)
            sizes.append(cur_bytes)
            rows.append(cur_rows)
            cur, cur_bytes, cur_rows = [], 0, 0
        cur.append(rg_i)
        cur_bytes += nb
        cur_rows += rg.num_rows
    if cur:
        groups.append(cur)
        sizes.append(cur_bytes)
        rows.append(cur_rows)
    if len(groups) <= 1:
        return None

    def make_read(rgs: List[int]):
        def read():
            pf = pq.ParquetFile(path)
            for rb in pf.iter_batches(batch_size=_scan_batch_rows(),
                                      row_groups=rgs, columns=columns):
                t = pa.Table.from_batches([rb])
                yield MicroPartition.from_arrow(t).cast_to_schema(out_schema)

        return _maybe_prefetch(read)

    from ..observability.metrics import registry

    registry().inc("scan_tasks_split", len(groups))
    return [
        ScanTask(
            read=make_read(g),
            schema=out_schema,
            size_bytes=nb,
            num_rows=nr,
            filters_applied=False,
            limit_applied=False,
            source_label=f"{path}[rg{g[0]}..{g[-1]}]",
        )
        for g, nb, nr in zip(groups, sizes, rows)
    ]


def _maybe_prefetch(read_factory):
    """Budgeted decode-ahead for scan readers: under a host memory budget,
    run the parquet decode loop on the spill IO pool with a depth-bounded
    queue (DAFT_TPU_SPILL_PREFETCH_BATCHES), overlapping decompress with the
    operators consuming the scan. Unbudgeted queries get the factory back
    untouched — they never see the pool, queue, or counters (the
    zero-overhead guard); the budget check runs at READ time, not task-build
    time, so tasks built outside a query scope still honor the budget their
    executing query runs under."""

    def read_prefetched():
        from ..config import execution_config
        from ..memory.manager import manager

        cfg = execution_config()
        if (manager().limit_bytes() > 0 and cfg.spill_io_threads > 0
                and cfg.spill_prefetch_batches > 0):
            from ..memory.spill import prefetch_iter

            yield from prefetch_iter(read_factory, cfg.spill_prefetch_batches,
                                     cfg.spill_io_threads, counters=False)
        else:
            yield from read_factory()

    return read_prefetched


def _make_reader(path: str, columns, arrow_filter, limit, out_schema: Schema):
    from .object_store import is_remote

    if is_remote(path):
        def read_remote():
            from .object_store import open_input

            # ranged-read file: column pruning downloads only touched byte
            # ranges; predicate re-applied by the executor (filters_applied is
            # False for remote tasks)
            pf = pq.ParquetFile(open_input(path))
            produced = 0
            for rb in pf.iter_batches(batch_size=_scan_batch_rows(), columns=columns):
                if limit is not None and produced >= limit:
                    return
                t = pa.Table.from_batches([rb])
                if limit is not None and produced + t.num_rows > limit:
                    t = t.slice(0, limit - produced)
                produced += t.num_rows
                yield MicroPartition.from_arrow(t).cast_to_schema(out_schema)

        return _maybe_prefetch(read_remote)

    def read():
        ds = pads.dataset(path, format="parquet")
        scanner = ds.scanner(columns=columns, filter=arrow_filter,
                             batch_size=_scan_batch_rows())
        produced = 0
        for rb in scanner.to_batches():
            if limit is not None and produced >= limit:
                return
            t = pa.Table.from_batches([rb])
            if limit is not None and produced + t.num_rows > limit:
                t = t.slice(0, limit - produced)
            produced += t.num_rows
            mp = MicroPartition.from_arrow(t)
            yield mp.cast_to_schema(out_schema)

    return _maybe_prefetch(read)


def _expr_to_arrow_filter(expr) -> Optional[pads.Expression]:
    """Best-effort translation of our Expression IR to a pyarrow dataset filter.
    Returns None when any node has no arrow equivalent (filter then re-applied
    post-scan by the executor; translate() checks filters_applied)."""
    import pyarrow.compute as pc

    from ..expressions import Between, BinaryOp, ColumnRef, IsIn, Literal, UnaryOp

    def conv(e):
        if isinstance(e, ColumnRef):
            return pads.field(e._name)
        if isinstance(e, Literal):
            return pa.scalar(e.value)
        if isinstance(e, BinaryOp):
            l, r = conv(e.left), conv(e.right)
            if l is None or r is None:
                return None
            ops = {
                "add": lambda a, b: a + b, "sub": lambda a, b: a - b,
                "mul": lambda a, b: a * b, "div": lambda a, b: a / b,
                "eq": lambda a, b: a == b, "neq": lambda a, b: a != b,
                "lt": lambda a, b: a < b, "le": lambda a, b: a <= b,
                "gt": lambda a, b: a > b, "ge": lambda a, b: a >= b,
                "and": lambda a, b: a & b, "or": lambda a, b: a | b,
            }
            f = ops.get(e.op)
            return f(l, r) if f else None
        if isinstance(e, UnaryOp):
            c = conv(e.child)
            if c is None:
                return None
            if e.op == "not":
                return ~c
            if e.op == "is_null":
                return c.is_null()
            if e.op == "not_null":
                return c.is_valid()
            return None
        if isinstance(e, Between):
            c, lo, hi = conv(e.child), conv(e.lower), conv(e.upper)
            if c is None or lo is None or hi is None:
                return None
            return (c >= lo) & (c <= hi)
        if isinstance(e, IsIn):
            c = conv(e.child)
            vals = []
            for item in e.items:
                if not isinstance(item, Literal):
                    return None
                vals.append(item.value)
            return c.isin(vals) if c is not None else None
        return None

    try:
        return conv(expr)
    except Exception:  # lint: ignore[broad-except] -- unconvertible filter: scan without pushdown
        return None
