"""Parquet scan operator.

Reference parity: src/daft-parquet/src/read.rs:440,490 (bulk + streaming reads,
row-group pruning via statistics) and src/daft-scan/src/glob.rs. Host-side IO is
pyarrow-backed; tasks split per file (and per row-group for large files) so the
executor can parallelize and the optimizer's pushdowns (columns/filters/limit)
prune IO before any byte is read.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Union

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from ..core.micropartition import MicroPartition
from ..schema import Schema
from .paths import expand_paths
from .scan import Pushdowns, ScanOperator, ScanTask

def _scan_batch_rows() -> int:
    """Target rows per emitted MicroPartition batch chunk — the config's
    morsel_size_rows (read at READ time, not plan time, so DAFT_TPU_MORSEL_SIZE
    and batching-strategy resizes reach scan-fed pipelines). Was a hardcoded
    128Ki that silently ignored the knob (PR 4 unified the executor's
    partial-agg splitter; this closes the scan side)."""
    from ..config import execution_config

    return max(execution_config().morsel_size_rows, 1)


class ParquetScanOperator(ScanOperator):
    def __init__(self, path: Union[str, List[str]], schema: Optional[Schema] = None,
                 row_groups_per_task: Optional[int] = None, **_options):
        self._paths = expand_paths(path, (".parquet", ".pq"))
        if not self._paths:
            raise FileNotFoundError(f"no parquet files matched {path!r}")
        self._schema = schema
        self._row_groups_per_task = row_groups_per_task

    def name(self) -> str:
        return f"ParquetScan({len(self._paths)} files)"

    def schema(self) -> Schema:
        if self._schema is None:
            from .object_store import open_input

            # schema inference from the first file (reference: schema_inference.rs);
            # remote objects read only the footer via ranged reads
            self._schema = Schema.from_arrow(pq.read_schema(open_input(self._paths[0])))
        return self._schema

    def can_absorb_select(self) -> bool:
        return True

    def can_absorb_filter(self) -> bool:
        return True

    def can_absorb_limit(self) -> bool:
        return True

    def approx_num_rows(self, pushdowns: Pushdowns) -> Optional[float]:
        from .object_store import open_input

        total = 0
        for p in self._paths:
            try:
                total += pq.ParquetFile(open_input(p)).metadata.num_rows
            except Exception:  # lint: ignore[broad-except] -- row estimate is advisory
                return None
        if pushdowns.limit is not None:
            total = min(total, pushdowns.limit)
        return float(total)

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        schema = self.schema()
        columns = pushdowns.columns
        out_schema = Schema([schema[c] for c in columns]) if columns is not None else schema
        arrow_filter = _expr_to_arrow_filter(pushdowns.filters) if pushdowns.filters is not None else None

        from .object_store import is_remote

        tasks = []
        conjuncts = _zone_map_conjuncts(pushdowns.filters) if pushdowns.filters is not None else []
        for path in self._paths:
            if conjuncts and _file_prunable(path, conjuncts):
                continue  # zone map proved no row can match (metadata-only read)
            tasks.append(ScanTask(
                read=_make_reader(path, columns, arrow_filter, pushdowns.limit, out_schema),
                schema=out_schema,
                size_bytes=os.path.getsize(path) if os.path.exists(path) else None,
                # remote readers don't evaluate the predicate; the executor
                # re-applies it post-scan
                filters_applied=arrow_filter is not None and not is_remote(path),
                limit_applied=False,
                source_label=path,
            ))
        return tasks


def _zone_map_conjuncts(expr) -> List[tuple]:
    """Extract (column, op, literal) constraints usable against row-group
    min/max statistics (reference: daft-parquet statistics/ + daft-stats
    zone-map pruning). Only top-level AND conjuncts of simple comparisons."""
    from ..expressions import Between, BinaryOp, ColumnRef, Literal

    out = []

    def walk(e):
        if isinstance(e, BinaryOp) and e.op == "and":
            walk(e.left)
            walk(e.right)
            return
        if isinstance(e, BinaryOp) and e.op in ("lt", "le", "gt", "ge", "eq"):
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
            if isinstance(e.left, ColumnRef) and isinstance(e.right, Literal):
                out.append((e.left._name, e.op, e.right.value))
            elif isinstance(e.right, ColumnRef) and isinstance(e.left, Literal):
                out.append((e.right._name, flip[e.op], e.left.value))
            return
        if isinstance(e, Between) and isinstance(e.child, ColumnRef):
            if isinstance(e.lower, Literal) and isinstance(e.upper, Literal):
                out.append((e.child._name, "ge", e.lower.value))
                out.append((e.child._name, "le", e.upper.value))

    walk(expr)
    return out


def _file_prunable(path: str, conjuncts: List[tuple]) -> bool:
    """True iff parquet row-group statistics PROVE no row satisfies the
    predicate — every row group must be excluded by some conjunct. Metadata
    only: remote objects read just the footer via ranged gets."""
    from .object_store import open_input

    try:
        md = pq.ParquetFile(open_input(path)).metadata
        for rg_i in range(md.num_row_groups):
            rg = md.row_group(rg_i)
            cols = {rg.column(i).path_in_schema: rg.column(i).statistics
                    for i in range(rg.num_columns)}
            excluded = False
            for name, op, value in conjuncts:
                st = cols.get(name)
                if st is None or not st.has_min_max:
                    continue
                try:
                    if op in ("lt",) and not (st.min < value):
                        excluded = True
                    elif op == "le" and not (st.min <= value):
                        excluded = True
                    elif op == "gt" and not (st.max > value):
                        excluded = True
                    elif op == "ge" and not (st.max >= value):
                        excluded = True
                    elif op == "eq" and not (st.min <= value <= st.max):
                        excluded = True
                except TypeError:
                    continue  # incomparable stats (e.g. logical-type mismatch)
                if excluded:
                    break
            if not excluded:
                return False  # this row group might match
        return md.num_row_groups > 0
    except Exception:  # lint: ignore[broad-except] -- never prune on metadata trouble
        return False


def _make_reader(path: str, columns, arrow_filter, limit, out_schema: Schema):
    from .object_store import is_remote

    if is_remote(path):
        def read_remote():
            from .object_store import open_input

            # ranged-read file: column pruning downloads only touched byte
            # ranges; predicate re-applied by the executor (filters_applied is
            # False for remote tasks)
            pf = pq.ParquetFile(open_input(path))
            produced = 0
            for rb in pf.iter_batches(batch_size=_scan_batch_rows(), columns=columns):
                if limit is not None and produced >= limit:
                    return
                t = pa.Table.from_batches([rb])
                if limit is not None and produced + t.num_rows > limit:
                    t = t.slice(0, limit - produced)
                produced += t.num_rows
                yield MicroPartition.from_arrow(t).cast_to_schema(out_schema)

        return read_remote

    def read():
        ds = pads.dataset(path, format="parquet")
        scanner = ds.scanner(columns=columns, filter=arrow_filter,
                             batch_size=_scan_batch_rows())
        produced = 0
        for rb in scanner.to_batches():
            if limit is not None and produced >= limit:
                return
            t = pa.Table.from_batches([rb])
            if limit is not None and produced + t.num_rows > limit:
                t = t.slice(0, limit - produced)
            produced += t.num_rows
            mp = MicroPartition.from_arrow(t)
            yield mp.cast_to_schema(out_schema)

    return read


def _expr_to_arrow_filter(expr) -> Optional[pads.Expression]:
    """Best-effort translation of our Expression IR to a pyarrow dataset filter.
    Returns None when any node has no arrow equivalent (filter then re-applied
    post-scan by the executor; translate() checks filters_applied)."""
    import pyarrow.compute as pc

    from ..expressions import Between, BinaryOp, ColumnRef, IsIn, Literal, UnaryOp

    def conv(e):
        if isinstance(e, ColumnRef):
            return pads.field(e._name)
        if isinstance(e, Literal):
            return pa.scalar(e.value)
        if isinstance(e, BinaryOp):
            l, r = conv(e.left), conv(e.right)
            if l is None or r is None:
                return None
            ops = {
                "add": lambda a, b: a + b, "sub": lambda a, b: a - b,
                "mul": lambda a, b: a * b, "div": lambda a, b: a / b,
                "eq": lambda a, b: a == b, "neq": lambda a, b: a != b,
                "lt": lambda a, b: a < b, "le": lambda a, b: a <= b,
                "gt": lambda a, b: a > b, "ge": lambda a, b: a >= b,
                "and": lambda a, b: a & b, "or": lambda a, b: a | b,
            }
            f = ops.get(e.op)
            return f(l, r) if f else None
        if isinstance(e, UnaryOp):
            c = conv(e.child)
            if c is None:
                return None
            if e.op == "not":
                return ~c
            if e.op == "is_null":
                return c.is_null()
            if e.op == "not_null":
                return c.is_valid()
            return None
        if isinstance(e, Between):
            c, lo, hi = conv(e.child), conv(e.lower), conv(e.upper)
            if c is None or lo is None or hi is None:
                return None
            return (c >= lo) & (c <= hi)
        if isinstance(e, IsIn):
            c = conv(e.child)
            vals = []
            for item in e.items:
                if not isinstance(item, Literal):
                    return None
                vals.append(item.value)
            return c.isin(vals) if c is not None else None
        return None

    try:
        return conv(expr)
    except Exception:  # lint: ignore[broad-except] -- unconvertible filter: scan without pushdown
        return None
