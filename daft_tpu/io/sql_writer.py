"""SQL table writer over plain DB-API connections.

Reference parity: DataFrame.write_sql (daft/dataframe/dataframe.py) — the
reference routes through SQLAlchemy; here any PEP 249 connection (or a zero-arg
factory returning one) works, which keeps the path dependency-free: stdlib
sqlite3 satisfies it out of the box, and psycopg2 / mysqlclient / duckdb
connections plug in unchanged.
"""

from __future__ import annotations

from typing import Any

from ..datatype import DataType


def _sql_type(dt: DataType) -> str:
    if dt.is_integer() or dt.is_boolean():
        return "BIGINT"
    if dt.is_floating() or dt.is_decimal():
        return "DOUBLE PRECISION"
    if dt.is_temporal():
        return "TIMESTAMP"
    if dt.is_binary():
        return "BLOB"
    return "TEXT"


def _quote(name: str) -> str:
    return '"' + name.replace('"', '""') + '"'


def write_sql(df, table_name: str, connection, mode: str = "append"):
    """mode: "append" (create if absent), "overwrite" (drop + recreate),
    "error" (fail if the table exists). Returns a DataFrame with the row
    count written."""
    # a live connection has .cursor(); anything else is a zero-arg factory
    conn = connection if hasattr(connection, "cursor") else connection()
    cur = conn.cursor()
    schema = df.schema
    cols = schema.column_names()
    qtable = _quote(table_name)

    ddl_cols = ", ".join(f"{_quote(f.name)} {_sql_type(f.dtype)}" for f in schema)
    if mode == "overwrite":
        cur.execute(f"DROP TABLE IF EXISTS {qtable}")
        cur.execute(f"CREATE TABLE {qtable} ({ddl_cols})")
    elif mode == "error":
        cur.execute(f"CREATE TABLE {qtable} ({ddl_cols})")
    else:  # append
        cur.execute(f"CREATE TABLE IF NOT EXISTS {qtable} ({ddl_cols})")

    placeholder = ", ".join(["?"] * len(cols))
    paramstyle = getattr(_module_of(conn), "paramstyle", "qmark")
    if paramstyle in ("format", "pyformat"):
        placeholder = ", ".join(["%s"] * len(cols))
    insert = (f"INSERT INTO {qtable} ({', '.join(_quote(c) for c in cols)}) "
              f"VALUES ({placeholder})")

    total = 0
    data = df.to_pydict()
    rows = list(zip(*[_plainify(data[c]) for c in cols])) if cols else []
    if rows:
        cur.executemany(insert, rows)
        total = len(rows)
    conn.commit()

    import daft_tpu

    return daft_tpu.from_pydict({"table": [table_name], "rows": [total]})


def _module_of(conn) -> Any:
    import sys

    mod = type(conn).__module__.split(".")[0]
    return sys.modules.get(mod)


def _plainify(values: list) -> list:
    """DB-API drivers reject numpy scalars and nested values; stringify the
    exotic ones."""
    out = []
    for v in values:
        if hasattr(v, "item"):
            v = v.item()
        if isinstance(v, (list, dict, tuple, set, bytes)) and not isinstance(v, bytes):
            v = repr(v)
        out.append(v)
    return out
