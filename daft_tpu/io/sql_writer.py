"""SQL table writer over plain DB-API connections.

Reference parity: DataFrame.write_sql (daft/dataframe/dataframe.py) — the
reference routes through SQLAlchemy; here any PEP 249 connection (or a zero-arg
factory returning one) works, which keeps the path dependency-free: stdlib
sqlite3 satisfies it out of the box, and psycopg2 / mysqlclient / duckdb
connections plug in unchanged.
"""

from __future__ import annotations

from typing import Any

from ..datatype import DataType


def _sql_type(dt: DataType) -> str:
    if dt.is_integer() or dt.is_boolean():
        return "BIGINT"
    if dt.is_floating() or dt.is_decimal():
        return "DOUBLE PRECISION"
    if dt.is_temporal():
        return "TIMESTAMP"
    if dt.is_binary():
        return "BLOB"
    return "TEXT"


def _quote(name: str) -> str:
    return '"' + name.replace('"', '""') + '"'


def write_sql(df, table_name: str, connection, mode: str = "append"):
    """mode: "append" (create if absent), "overwrite" (drop + recreate),
    "error" (fail if the table exists). Returns a DataFrame with the row
    count written."""
    # a live connection has .cursor(); anything else is a zero-arg factory
    conn = connection if hasattr(connection, "cursor") else connection()
    cur = conn.cursor()
    schema = df.schema
    cols = schema.column_names()
    qtable = _quote(table_name)

    ddl_cols = ", ".join(f"{_quote(f.name)} {_sql_type(f.dtype)}" for f in schema)
    if mode == "overwrite":
        cur.execute(f"DROP TABLE IF EXISTS {qtable}")
        cur.execute(f"CREATE TABLE {qtable} ({ddl_cols})")
    elif mode == "error":
        cur.execute(f"CREATE TABLE {qtable} ({ddl_cols})")
    else:  # append
        cur.execute(f"CREATE TABLE IF NOT EXISTS {qtable} ({ddl_cols})")

    placeholder = ", ".join(["?"] * len(cols))
    paramstyle = getattr(_module_of(conn), "paramstyle", "qmark")
    if paramstyle in ("format", "pyformat"):
        placeholder = ", ".join(["%s"] * len(cols))
    insert = (f"INSERT INTO {qtable} ({', '.join(_quote(c) for c in cols)}) "
              f"VALUES ({placeholder})")

    total = 0
    data = df.to_pydict()
    rows = list(zip(*[_plainify(data[c]) for c in cols])) if cols else []
    if rows:
        cur.executemany(insert, rows)
        total = len(rows)
    conn.commit()

    import daft_tpu

    return daft_tpu.from_pydict({"table": [table_name], "rows": [total]})


def _module_of(conn) -> Any:
    import sys

    mod = type(conn).__module__.split(".")[0]
    return sys.modules.get(mod)


def _plainify(values: list) -> list:
    """DB-API drivers reject numpy scalars and nested values; stringify the
    exotic ones."""
    out = []
    for v in values:
        if hasattr(v, "item"):
            v = v.item()
        if isinstance(v, (list, dict, tuple, set, bytes)) and not isinstance(v, bytes):
            v = repr(v)
        out.append(v)
    return out


def read_sql(sql: str, connection, partition_col=None, num_partitions: int = 1):
    """Run a SQL query through a DB-API connection (or zero-arg factory) and
    return the result as arrow data (reference: daft.read_sql via ConnectorX/
    SQLAlchemy; plain DB-API keeps it dependency-free). With partition_col +
    num_partitions > 1, the query is split into range partitions like the
    reference's partitioned reads."""
    conn = connection if hasattr(connection, "cursor") else connection()

    def _fetch(q: str):
        cur = conn.cursor()
        cur.execute(q)
        cols = [d[0] for d in cur.description]
        rows = cur.fetchall()
        return {c: [r[i] for r in rows] for i, c in enumerate(cols)}

    import daft_tpu

    if partition_col is None or num_partitions <= 1:
        return daft_tpu.from_pydict(_fetch(sql))

    bounds = _fetch(f"SELECT MIN({partition_col}) lo, MAX({partition_col}) hi "
                    f"FROM ({sql}) __b__")
    lo, hi = bounds["lo"][0], bounds["hi"][0]
    if lo is None:
        return daft_tpu.from_pydict(_fetch(sql))
    import decimal

    if not isinstance(lo, (int, float, decimal.Decimal)) \
            or not isinstance(hi, (int, float, decimal.Decimal)) \
            or isinstance(lo, bool) or isinstance(hi, bool):
        # non-numeric partition column (dates/strings): range arithmetic below
        # doesn't apply — read unpartitioned rather than raising mid-plan
        # (reference supports these via percentile-based partitioning;
        # src/daft-connectors sql percentile path — not implemented here)
        return daft_tpu.from_pydict(_fetch(sql))
    step = (hi - lo) / num_partitions
    parts = []
    for i in range(num_partitions):
        a = lo + step * i
        b = hi if i == num_partitions - 1 else lo + step * (i + 1)
        op = "<=" if i == num_partitions - 1 else "<"
        parts.append(daft_tpu.from_pydict(_fetch(
            f"SELECT * FROM ({sql}) __p__ WHERE {partition_col} >= {a} "
            f"AND {partition_col} {op} {b}")))
    out = parts[0]
    for p in parts[1:]:
        out = out.concat(p)
    return out
