"""File writers: parquet/csv/json with partitioned + size-targeted file rotation.

Reference parity: src/daft-writers (AsyncFileWriter/WriterFactory, physical.rs:21,
partition.rs, batch_file_writer.rs). The Sink physical node calls
WriteInfo.execute_write; the result stream is a manifest of written file paths
(reference: CommitWriteSink emits a MicroPartition of paths).
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, Iterator, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..core.micropartition import MicroPartition
from ..core.recordbatch import RecordBatch
from ..datatype import DataType, Field
from ..schema import Schema

# rotate output files at ~this many bytes of arrow data (reference:
# parquet_target_filesize 512MB with inflation factor; scaled down is fine locally)
_TARGET_FILE_BYTES = 512 * 1024 * 1024


class WriteInfo:
    def __init__(self, format: str, root_dir: str, options: Dict[str, Any],
                 partition_cols: Optional[List[Any]] = None, write_mode: str = "append",
                 checkpoint=None):
        if format not in ("parquet", "csv", "json"):
            raise ValueError(f"unsupported write format {format!r}")
        self.format = format
        self.root_dir = root_dir
        self.options = options
        self.partition_cols = partition_cols
        self.write_mode = write_mode
        # (CheckpointStore, key_column): skip-on-rerun + file staging for 2PC
        # sinks (reference: daft-checkpoint store.rs lifecycle)
        self.checkpoint = checkpoint

    def __repr__(self) -> str:
        return f"{self.format}://{self.root_dir}"

    def result_schema(self) -> Schema:
        return Schema([Field("path", DataType.string())])

    def execute_write(self, parts: Iterator[MicroPartition], input_schema: Schema) -> Iterator[MicroPartition]:
        from .object_store import is_remote

        if self.checkpoint is not None:
            yield from self._execute_checkpointed(parts, input_schema)
            return
        if is_remote(self.root_dir):
            yield from self._execute_remote_write(parts, input_schema)
            return
        os.makedirs(self.root_dir, exist_ok=True)
        if self.write_mode == "overwrite":
            _clear_dir(self.root_dir)

        written: List[str] = []
        if self.partition_cols:
            written = self._write_partitioned(parts, input_schema)
        else:
            writer = _FileWriter(self.format, self.root_dir, self.options, input_schema)
            for part in parts:
                for b in part.batches:
                    writer.write(b)
            written = writer.close()
        yield MicroPartition.from_pydict({"path": written}).cast_to_schema(self.result_schema())

    def _execute_checkpointed(self, parts: Iterator[MicroPartition],
                              input_schema: Schema) -> Iterator[MicroPartition]:
        """Checkpointed write: rows whose key was sealed by a previous run are
        skipped; this run's keys stage under a fresh CheckpointId which seals
        (with the written file manifest) only after every batch succeeded
        (reference: stage_checkpoint_keys.rs + CheckpointStore lifecycle)."""
        import uuid as _uuid

        from ..expressions import col as _col
        from ..expressions.eval import eval_expression

        store, key_col = self.checkpoint
        done = store.get_checkpointed_keys()
        cid = _uuid.uuid4().hex[:16]

        def filtered_parts():
            for part in parts:
                for b in part.batches:
                    if b.num_rows == 0:
                        continue
                    keys = eval_expression(b, _col(key_col)).to_pylist()
                    if done:
                        import numpy as np

                        keep = np.array([k not in done for k in keys], dtype=bool)
                        if not keep.any():
                            continue
                        if not keep.all():
                            from ..core.series import Series

                            b = b.filter_by_mask(Series.from_numpy(keep, "m"))
                            keys = [k for k, kp in zip(keys, keep) if kp]
                    store.stage_keys(cid, keys)
                    yield MicroPartition(b.schema, [b])

        inner = WriteInfo(self.format, self.root_dir, self.options,
                          self.partition_cols, self.write_mode)
        manifest = list(inner.execute_write(filtered_parts(), input_schema))
        files = [p for mp in manifest for p in mp.to_pydict().get("path", [])]
        store.stage_files(cid, files)
        store.checkpoint(cid)  # seal: keys+files visible atomically
        yield from manifest

    def _execute_remote_write(self, parts: Iterator[MicroPartition],
                              input_schema: Schema) -> Iterator[MicroPartition]:
        """Remote sinks: write to a local staging dir, then upload each file to
        the object store (reference: daft-writers storage_backend.rs)."""
        import shutil
        import tempfile

        from .object_store import resolve_source

        source, rel_root = resolve_source(self.root_dir)
        scheme = self.root_dir.split("://", 1)[0] + "://"
        if self.write_mode == "overwrite":
            for key in source.ls(rel_root.rstrip("/") + "/"):
                source.delete(key)
        staging = tempfile.mkdtemp(prefix="daft_tpu_write_")
        local = WriteInfo(self.format, staging, self.options,
                          self.partition_cols, write_mode="append")
        try:
            manifest = list(local.execute_write(parts, input_schema))
            remote_paths: List[str] = []
            for mp in manifest:
                for local_path in mp.to_pydict().get("path", []):
                    rel = os.path.relpath(local_path, staging)
                    key = rel_root.rstrip("/") + "/" + rel.replace(os.sep, "/")
                    # NOTE: whole-object PUT (SigV4 hashes the payload); large
                    # staged files are held in memory per upload — multipart
                    # streaming upload is the planned upgrade (reference:
                    # daft-io multipart.rs)
                    with open(local_path, "rb") as f:
                        source.put(key, f.read())
                    remote_paths.append(scheme + key)
            yield MicroPartition.from_pydict(
                {"path": remote_paths}).cast_to_schema(self.result_schema())
        finally:
            shutil.rmtree(staging, ignore_errors=True)

    def _write_partitioned(self, parts: Iterator[MicroPartition], input_schema: Schema) -> List[str]:
        from ..expressions.eval import eval_expression

        writers: Dict[tuple, _FileWriter] = {}
        written: List[str] = []
        for part in parts:
            for b in part.batches:
                keys = [eval_expression(b, e) for e in self.partition_cols]
                pieces, key_batch = b.partition_by_value(keys)
                key_rows = key_batch.to_pylist()
                for piece, krow in zip(pieces, key_rows):
                    if piece.num_rows == 0:
                        continue
                    kt = tuple(sorted(krow.items()))
                    if kt not in writers:
                        subdir = os.path.join(
                            self.root_dir,
                            *[f"{k}={_hive_str(v)}" for k, v in krow.items()],
                        )
                        os.makedirs(subdir, exist_ok=True)
                        writers[kt] = _FileWriter(self.format, subdir, self.options, input_schema)
                    writers[kt].write(piece)
        for w in writers.values():
            written.extend(w.close())
        return written


def _hive_str(v) -> str:
    return "__HIVE_DEFAULT_PARTITION__" if v is None else str(v)


def _clear_dir(d: str) -> None:
    for root, _dirs, files in os.walk(d):
        for f in files:
            os.unlink(os.path.join(root, f))


class _FileWriter:
    """Size-targeted rotating writer for one directory."""

    def __init__(self, format: str, dir: str, options: Dict[str, Any], schema: Schema):
        self.format = format
        self.dir = dir
        self.options = options
        self.schema = schema
        self.buffer: List[RecordBatch] = []
        self.buffered_bytes = 0
        self.written: List[str] = []

    def write(self, batch: RecordBatch) -> None:
        if batch.num_rows == 0:
            return
        self.buffer.append(batch)
        self.buffered_bytes += batch.size_bytes()
        if self.buffered_bytes >= _TARGET_FILE_BYTES:
            self._flush()

    def _flush(self) -> None:
        if not self.buffer:
            return
        table = pa.concat_tables([b.to_arrow() for b in self.buffer])
        name = f"{uuid.uuid4().hex}"
        if self.format == "parquet":
            path = os.path.join(self.dir, name + ".parquet")
            pq.write_table(table, path, compression=self.options.get("compression", "snappy"))
        elif self.format == "csv":
            import pyarrow.csv as pacsv

            path = os.path.join(self.dir, name + ".csv")
            pacsv.write_csv(table, path)
        else:
            path = os.path.join(self.dir, name + ".jsonl")
            with open(path, "w") as f:
                import json as _json

                for row in table.to_pylist():
                    f.write(_json.dumps(row, default=str) + "\n")
        self.written.append(path)
        self.buffer = []
        self.buffered_bytes = 0

    def close(self) -> List[str]:
        self._flush()
        return self.written
