"""JSON-lines scan operator (reference parity: src/daft-json — line-split streaming
reads with schema inference; local-filesystem subset, pyarrow-backed)."""

from __future__ import annotations

import os
from typing import List, Optional, Union

import pyarrow as pa
import pyarrow.json as pajson

from ..core.micropartition import MicroPartition
from ..schema import Schema
from .paths import expand_paths
from .scan import Pushdowns, ScanOperator, ScanTask


class JsonScanOperator(ScanOperator):
    def __init__(self, path: Union[str, List[str]], schema: Optional[Schema] = None, **_options):
        self._paths = expand_paths(path, (".json", ".jsonl", ".ndjson"))
        if not self._paths:
            raise FileNotFoundError(f"no json files matched {path!r}")
        self._schema = schema

    def name(self) -> str:
        return f"JsonScan({len(self._paths)} files)"

    def schema(self) -> Schema:
        if self._schema is None:
            from .object_store import open_input
            t = pajson.read_json(open_input(self._paths[0]))
            self._schema = Schema.from_arrow(t.schema)
        return self._schema

    def can_absorb_select(self) -> bool:
        return True

    def can_absorb_limit(self) -> bool:
        return True

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        schema = self.schema()
        columns = pushdowns.columns
        out_schema = Schema([schema[c] for c in columns]) if columns is not None else schema
        tasks = []
        for path in self._paths:
            def make(path=path):
                def read():
                    from .object_store import open_input

                    t = pajson.read_json(open_input(path))
                    if columns is not None:
                        t = t.select(columns)
                    if pushdowns.limit is not None:
                        t = t.slice(0, pushdowns.limit)
                    yield MicroPartition.from_arrow(t).cast_to_schema(out_schema)

                return read

            tasks.append(ScanTask(
                read=make(),
                schema=out_schema,
                size_bytes=os.path.getsize(path) if os.path.exists(path) else None,
                source_label=path,
            ))
        return tasks
