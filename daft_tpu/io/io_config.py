"""IO configuration (reference parity: src/common/io-config — IOConfig with
S3/HTTP sub-configs, attachable per-read or process-wide)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace
from typing import Optional


@dataclass(frozen=True)
class S3Config:
    endpoint_url: Optional[str] = field(
        default_factory=lambda: os.environ.get("DAFT_TPU_S3_ENDPOINT") or None)
    region: str = field(default_factory=lambda: os.environ.get("AWS_REGION", "us-east-1"))
    access_key_id: Optional[str] = field(
        default_factory=lambda: os.environ.get("AWS_ACCESS_KEY_ID") or None)
    secret_access_key: Optional[str] = field(
        default_factory=lambda: os.environ.get("AWS_SECRET_ACCESS_KEY") or None)
    session_token: Optional[str] = field(
        default_factory=lambda: os.environ.get("AWS_SESSION_TOKEN") or None)
    anonymous: bool = False
    max_retries: int = 4
    retry_initial_backoff_ms: int = 100
    # path-style addressing (endpoint/bucket/key) — required by most S3 mocks
    force_path_style: bool = True


@dataclass(frozen=True)
class GCSConfig:
    """Google Cloud Storage over the JSON API (reference: io-config GCSConfig +
    src/daft-io/src/google_cloud.rs). Auth: bearer token (env
    GCS_TOKEN / GOOGLE_CLOUD_TOKEN) or anonymous; endpoint override targets
    fake-gcs-server-style mocks."""

    endpoint_url: Optional[str] = field(
        default_factory=lambda: os.environ.get("DAFT_TPU_GCS_ENDPOINT") or None)
    token: Optional[str] = field(
        default_factory=lambda: os.environ.get("GCS_TOKEN")
        or os.environ.get("GOOGLE_CLOUD_TOKEN") or None)
    anonymous: bool = False
    max_retries: int = 4
    retry_initial_backoff_ms: int = 100


@dataclass(frozen=True)
class AzureConfig:
    """Azure Blob Storage REST (reference: io-config AzureConfig +
    src/daft-io/src/azure_blob.rs). Auth: SAS token or anonymous (shared-key
    signing is not implemented — use SAS); endpoint override targets Azurite."""

    storage_account: Optional[str] = field(
        default_factory=lambda: os.environ.get("AZURE_STORAGE_ACCOUNT") or None)
    sas_token: Optional[str] = field(
        default_factory=lambda: os.environ.get("AZURE_STORAGE_SAS_TOKEN") or None)
    endpoint_url: Optional[str] = field(
        default_factory=lambda: os.environ.get("DAFT_TPU_AZURE_ENDPOINT") or None)
    anonymous: bool = False
    max_retries: int = 4
    retry_initial_backoff_ms: int = 100


@dataclass(frozen=True)
class HTTPConfig:
    max_retries: int = 4
    retry_initial_backoff_ms: int = 100
    user_agent: str = "daft-tpu/0.1"


@dataclass(frozen=True)
class IOConfig:
    s3: S3Config = field(default_factory=S3Config)
    gcs: GCSConfig = field(default_factory=GCSConfig)
    azure: AzureConfig = field(default_factory=AzureConfig)
    http: HTTPConfig = field(default_factory=HTTPConfig)


_default: Optional[IOConfig] = None


def io_config() -> IOConfig:
    global _default
    if _default is None:
        _default = IOConfig()
    return _default


def set_io_config(config: Optional[IOConfig] = None, **kwargs) -> IOConfig:
    """Set the process-default IOConfig (or replace fields on the current one)."""
    global _default
    if config is not None:
        _default = config
    elif kwargs:
        _default = replace(io_config(), **kwargs)
    return io_config()
