"""Apache Hudi copy-on-write table read support.

Reference parity: daft/io/hudi/ (HudiScanOperator + the pyhudi mini-client:
timeline.py loads completed commit instants from .hoodie/, filegroup.py keeps
file slices per file group and serves the latest, table.py walks partitions).
The protocol is implemented directly:

    {table}/.hoodie/hoodie.properties        table config (partition fields)
    {table}/.hoodie/{instant}.commit         completed write commits (JSON)
    {table}/{partition}/{fileId}_{writeToken}_{instant}.parquet   base files

Snapshot read = for every file group (fileId within a partition), the base
file with the newest commit time that is <= the latest COMPLETED instant —
uncommitted/inflight writes are invisible. Merge-on-read tables (log files)
raise clearly rather than returning wrong answers.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional

from ..schema import Schema
from .scan import Pushdowns, ScanOperator, ScanTask

_BASE_FILE_RE = re.compile(r"^(?P<fid>[^_]+)_(?P<token>[^_]+)_(?P<instant>[^.]+)\.parquet$")


def _load_properties(table_path: str) -> Dict[str, str]:
    path = os.path.join(table_path, ".hoodie", "hoodie.properties")
    if not os.path.exists(path):
        raise FileNotFoundError(f"not a hudi table (no .hoodie/hoodie.properties): {table_path}")
    props: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            k, _, v = line.partition("=")
            props[k.strip()] = v.strip()
    return props


def _completed_instants(table_path: str):
    """(sorted completed instant timestamps, replaced file-group ids).
    Reference: timeline.py _load_completed_commit_instants — only bare
    `.commit` / `.replacecommit` files count; `.requested` / `.inflight`
    are pending. Replacecommits (clustering / insert_overwrite) contribute
    partitionToReplaceFileIds: those file groups are dead to snapshot reads."""
    hoodie = os.path.join(table_path, ".hoodie")
    out = []
    replaced = set()  # (partition_path, file_id)
    for n in os.listdir(hoodie):
        if n.endswith(".commit"):
            out.append(n[: -len(".commit")])
        elif n.endswith(".replacecommit"):
            out.append(n[: -len(".replacecommit")])
            try:
                with open(os.path.join(hoodie, n)) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = {}
            for part, fids in (meta.get("partitionToReplaceFileIds") or {}).items():
                for fid in fids:
                    replaced.add((part, fid))
    return sorted(out), replaced


def _partition_dirs(table_path: str) -> List[str]:
    """Relative partition paths: every directory (or the root) holding base
    files, skipping the .hoodie metadata tree."""
    out = []
    for dirpath, dirnames, filenames in os.walk(table_path):
        dirnames[:] = [d for d in dirnames if not d.startswith(".hoodie")]
        if any(_BASE_FILE_RE.match(n) for n in filenames):
            rel = os.path.relpath(dirpath, table_path)
            out.append("" if rel == "." else rel)
    return sorted(out)


class HudiScanOperator(ScanOperator):
    """Snapshot reader over a local/posix Hudi CoW table."""

    def __init__(self, table_path: str):
        import pyarrow.parquet as pq

        self.table_path = table_path
        self.props = _load_properties(table_path)
        table_type = self.props.get("hoodie.table.type", "COPY_ON_WRITE")
        if table_type != "COPY_ON_WRITE":
            raise NotImplementedError(
                f"hudi table type {table_type} is not supported (CoW only)")
        self._instants, self._replaced = _completed_instants(table_path)
        self._files = self._latest_file_slices()
        if not self._files:
            raise ValueError(f"hudi table has no committed base files: {table_path}")
        arrow_schema = pq.read_schema(self._files[0])
        self._schema = Schema.from_arrow(arrow_schema)

    def _latest_file_slices(self) -> List[str]:
        """One base file per file group: the newest committed slice
        (reference: filegroup.py get_latest_file_slice)."""
        if not self._instants:
            return []
        committed = set(self._instants)
        chosen: Dict[tuple, tuple] = {}  # (partition, fileId) -> (instant, path)
        for part in _partition_dirs(self.table_path):
            pdir = os.path.join(self.table_path, part) if part else self.table_path
            for n in os.listdir(pdir):
                if n.endswith(".log") or ".log." in n:
                    raise NotImplementedError(
                        "hudi merge-on-read log files are not supported")
                m = _BASE_FILE_RE.match(n)
                if m is None:
                    continue
                if m.group("instant") not in committed:
                    continue  # uncommitted write: invisible to snapshot reads
                if (part, m.group("fid")) in self._replaced:
                    continue  # clustered/overwritten file group: superseded
                key = (part, m.group("fid"))
                cur = chosen.get(key)
                if cur is None or m.group("instant") > cur[0]:
                    chosen[key] = (m.group("instant"), os.path.join(pdir, n))
        return [p for _i, p in sorted(chosen.values())]

    def name(self) -> str:
        return f"HudiScan({self.props.get('hoodie.table.name', self.table_path)})"

    def schema(self) -> Schema:
        return self._schema

    def can_absorb_select(self) -> bool:
        return True

    def can_absorb_filter(self) -> bool:
        return True

    def can_absorb_limit(self) -> bool:
        return False

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        from .parquet import _expr_to_arrow_filter

        columns = pushdowns.columns
        out_schema = Schema([self._schema[c] for c in columns]) \
            if columns is not None else self._schema
        arrow_filter = _expr_to_arrow_filter(pushdowns.filters) \
            if pushdowns.filters is not None else None

        tasks: List[ScanTask] = []
        for path in self._files:
            tasks.append(self._task(path, columns, arrow_filter, out_schema))
        return tasks

    def _task(self, path: str, columns, arrow_filter, out_schema: Schema) -> ScanTask:
        from .parquet import _make_reader

        # reuse the parquet reader (morsel-streamed, remote-capable) rather
        # than materializing a whole base file per task
        return ScanTask(read=_make_reader(path, columns, arrow_filter, None,
                                          out_schema),
                        schema=out_schema,
                        size_bytes=os.path.getsize(path), num_rows=None,
                        filters_applied=arrow_filter is not None,
                        limit_applied=False, source_label=path)

    def approx_num_rows(self, pushdowns: Pushdowns) -> Optional[float]:
        try:
            import pyarrow.parquet as pq

            total = sum(pq.read_metadata(p).num_rows for p in self._files)
            return float(total)
        except Exception:  # lint: ignore[broad-except] -- row estimate is advisory
            return None
