"""WARC (Web ARChive / Common Crawl) scan.

Reference parity: src/daft-warc — streaming WARC record reader powering the
Common Crawl dedup config. Parses WARC/1.0 and 1.1 records (plain or .gz),
yielding one row per record with the reference's column shape:
record id, type, target URI, date, content length, and the payload (both raw
bytes and a lossy UTF-8 string).
"""

from __future__ import annotations

import gzip
import io
import os
from typing import List, Optional, Union

from ..core.micropartition import MicroPartition
from ..datatype import DataType, Field
from ..schema import Schema
from .paths import expand_paths
from .scan import Pushdowns, ScanOperator, ScanTask

_RECORDS_PER_BATCH = 1024

_SCHEMA = Schema([
    Field("warc_record_id", DataType.string()),
    Field("warc_type", DataType.string()),
    Field("warc_target_uri", DataType.string()),
    Field("warc_date", DataType.string()),
    Field("content_length", DataType.int64()),
    Field("content_type", DataType.string()),
    Field("content", DataType.string()),
])


def _open_binary(path: str) -> io.BufferedIOBase:
    from .object_store import is_remote, resolve_source

    if is_remote(path):
        source, rel = resolve_source(path)
        raw: io.IOBase = io.BytesIO(source.get(rel))
    else:
        raw = open(path, "rb")
    if path.endswith(".gz"):
        return gzip.open(raw, "rb")
    return io.BufferedReader(raw) if not isinstance(raw, io.BufferedIOBase) else raw


def iter_warc_records(path: str):
    """Yield dict rows for each WARC record in a file (streaming)."""
    with _open_binary(path) as f:
        while True:
            # skip blank lines between records
            line = f.readline()
            if not line:
                return
            if line.strip() == b"":
                continue
            if not line.startswith(b"WARC/"):
                raise ValueError(f"{path}: expected WARC version line, got {line[:40]!r}")
            headers = {}
            while True:
                h = f.readline()
                if not h or h.strip() == b"":
                    break
                if b":" in h:
                    k, v = h.split(b":", 1)
                    headers[k.strip().lower().decode("ascii", "replace")] = \
                        v.strip().decode("utf-8", "replace")
            length = int(headers.get("content-length", "0"))
            payload = f.read(length)
            yield {
                "warc_record_id": headers.get("warc-record-id"),
                "warc_type": headers.get("warc-type"),
                "warc_target_uri": headers.get("warc-target-uri"),
                "warc_date": headers.get("warc-date"),
                "content_length": length,
                "content_type": headers.get("content-type"),
                "content": payload.decode("utf-8", "replace"),
            }


class WarcScanOperator(ScanOperator):
    def __init__(self, path: Union[str, List[str]], **_options):
        self._paths = expand_paths(path)
        if not self._paths:
            raise FileNotFoundError(f"no warc files matched {path!r}")

    def name(self) -> str:
        return f"WarcScan({len(self._paths)} files)"

    def schema(self) -> Schema:
        return _SCHEMA

    def can_absorb_limit(self) -> bool:
        return True

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        limit = pushdowns.limit
        tasks = []
        for path in self._paths:
            def make(path=path):
                def read():
                    produced = 0
                    rows: List[dict] = []
                    for rec in iter_warc_records(path):
                        if limit is not None and produced >= limit:
                            break
                        rows.append(rec)
                        produced += 1
                        if len(rows) >= _RECORDS_PER_BATCH:
                            yield _to_part(rows)
                            rows = []
                    if rows:
                        yield _to_part(rows)

                return read

            tasks.append(ScanTask(
                read=make(), schema=_SCHEMA,
                size_bytes=os.path.getsize(path) if os.path.exists(path) else None,
                limit_applied=False, source_label=path,
            ))
        return tasks


def _to_part(rows: List[dict]) -> MicroPartition:
    cols = {f.name: [r[f.name] for r in rows] for f in _SCHEMA}
    return MicroPartition.from_pydict(cols).cast_to_schema(_SCHEMA)
