"""Object-store abstraction: the engine's remote IO layer.

Reference parity: src/daft-io/src/object_io.rs:287 (ObjectSource trait —
get(range)/get_size/glob/ls/put/delete) with impls mirroring s3_like.rs
(SigV4-signed S3 REST over plain HTTP, path-style for mock compatibility),
http.rs (ranged GET), local.rs, and mock.rs:27 (failure-injection wrapper for
retry tests). Retries are exponential backoff + jitter on transient errors
(retry.rs). Everything is stdlib (urllib/hmac/hashlib) — no cloud SDK needed,
which also keeps the worker subprocesses light.

Scan operators route every path through resolve_source(); local paths keep
their fast direct-file path, s3://... and http(s)://... go through here.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import hmac
import os
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Iterator, List, Optional, Tuple

from .io_config import IOConfig, io_config


class ObjectSourceError(Exception):
    pass


class NotFoundError(ObjectSourceError):
    pass


class TransientError(ObjectSourceError):
    """Retryable: connection failures, throttling, 5xx."""


class ObjectSource:
    """One storage backend. Paths are source-relative (no scheme)."""

    def get(self, path: str, range: Optional[Tuple[int, int]] = None) -> bytes:
        """Read an object (or byte range [start, end))."""
        raise NotImplementedError

    def get_size(self, path: str) -> int:
        raise NotImplementedError

    def glob(self, pattern: str) -> List[str]:
        raise NotImplementedError

    def ls(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def put(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------


def with_retries(fn, max_retries: int, initial_backoff_ms: int):
    """Run fn() retrying TransientErrors with exponential backoff + jitter."""
    attempt = 0
    while True:
        try:
            return fn()
        except TransientError:
            attempt += 1
            if attempt > max_retries:
                raise
            backoff = initial_backoff_ms * (2 ** (attempt - 1)) / 1000.0
            time.sleep(backoff * (0.5 + random.random()))


# ---------------------------------------------------------------------------
# local filesystem
# ---------------------------------------------------------------------------


class LocalSource(ObjectSource):
    def get(self, path: str, range: Optional[Tuple[int, int]] = None) -> bytes:
        try:
            with open(path, "rb") as f:
                if range is None:
                    return f.read()
                f.seek(range[0])
                return f.read(range[1] - range[0])
        except FileNotFoundError as e:
            raise NotFoundError(str(e)) from e

    def get_size(self, path: str) -> int:
        try:
            return os.path.getsize(path)
        except FileNotFoundError as e:
            raise NotFoundError(str(e)) from e

    def glob(self, pattern: str) -> List[str]:
        import glob as _g

        return sorted(_g.glob(pattern, recursive=True))

    def ls(self, prefix: str) -> List[str]:
        if os.path.isdir(prefix):
            return sorted(os.path.join(prefix, n) for n in os.listdir(prefix))
        return []

    def put(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    def delete(self, path: str) -> None:
        try:
            os.unlink(path)
        except FileNotFoundError as e:
            raise NotFoundError(str(e)) from e


# ---------------------------------------------------------------------------
# HTTP(S)
# ---------------------------------------------------------------------------


def _http_request(url: str, method: str = "GET", headers: Optional[dict] = None,
                  data: Optional[bytes] = None, timeout: float = 60.0):
    req = urllib.request.Request(url, method=method, data=data,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        body = e.read() if hasattr(e, "read") else b""
        if e.code == 404:
            raise NotFoundError(f"{url}: 404") from e
        if e.code in (408, 429) or e.code >= 500:
            raise TransientError(f"{url}: HTTP {e.code}") from e
        raise ObjectSourceError(f"{url}: HTTP {e.code}: {body[:200]!r}") from e
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError) as e:
        raise TransientError(f"{url}: {e}") from e


class HTTPSource(ObjectSource):
    """Plain HTTP(S) objects. Paths here are full URLs."""

    def __init__(self, config: Optional[IOConfig] = None):
        self.cfg = (config or io_config()).http

    def _do(self, fn):
        return with_retries(fn, self.cfg.max_retries, self.cfg.retry_initial_backoff_ms)

    def get(self, path: str, range: Optional[Tuple[int, int]] = None) -> bytes:
        headers = {"User-Agent": self.cfg.user_agent}
        if range is not None:
            headers["Range"] = f"bytes={range[0]}-{range[1] - 1}"
        _status, _h, body = self._do(lambda: _http_request(path, headers=headers))
        return body

    def get_size(self, path: str) -> int:
        status, headers, _ = self._do(
            lambda: _http_request(path, method="HEAD",
                                  headers={"User-Agent": self.cfg.user_agent}))
        cl = headers.get("Content-Length")
        if cl is None:
            raise ObjectSourceError(f"{path}: no Content-Length")
        return int(cl)

    def glob(self, pattern: str) -> List[str]:
        raise ObjectSourceError("HTTP source does not support globs")




def _split_bucket(path: str) -> Tuple[str, str]:
    """"bucket/key" -> (bucket, key) — shared by the bucketed backends."""
    parts = path.split("/", 1)
    return parts[0], parts[1] if len(parts) > 1 else ""


def _prefix_glob(ls, pattern: str) -> List[str]:
    """List the longest literal prefix, filter client-side (reference:
    object_store_glob.rs prefix optimization). `*`/`?` do NOT cross `/`,
    `**` does. Shared by every bucketed backend so glob semantics cannot
    diverge."""
    cut = len(pattern)
    for i, ch in enumerate(pattern):
        if ch in "*?[":
            cut = i
            break
    prefix = pattern[:cut]
    prefix = prefix[: prefix.rfind("/") + 1] if "/" in prefix else prefix
    rx = _glob_to_regex(pattern)
    return sorted(p for p in ls(prefix) if rx.match(p))


# ---------------------------------------------------------------------------
# S3 (SigV4 over stdlib urllib; path-style endpoints; ListObjectsV2 glob)
# ---------------------------------------------------------------------------


def _sigv4_headers(cfg, method: str, host: str, canonical_uri: str,
                   query: str, payload: bytes) -> dict:
    """Minimal AWS Signature Version 4 for S3 (UNSIGNED when anonymous)."""
    now = _dt.datetime.now(_dt.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()
    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    if cfg.session_token:
        headers["x-amz-security-token"] = cfg.session_token
    if cfg.anonymous or not cfg.access_key_id:
        return {k: v for k, v in headers.items() if k != "host"}

    signed_names = sorted(headers)
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in signed_names)
    signed_headers = ";".join(signed_names)
    canonical_request = "\n".join([
        method, canonical_uri, query, canonical_headers, signed_headers, payload_hash,
    ])
    scope = f"{datestamp}/{cfg.region}/s3/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(f"AWS4{cfg.secret_access_key}".encode(), datestamp)
    k = _hmac(k, cfg.region)
    k = _hmac(k, "s3")
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={cfg.access_key_id}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}"
    )
    return {k: v for k, v in headers.items() if k != "host"}


class S3Source(ObjectSource):
    """S3-compatible object store. Paths are "bucket/key"."""

    def __init__(self, config: Optional[IOConfig] = None):
        self.cfg = (config or io_config()).s3
        if self.cfg.endpoint_url:
            self.endpoint = self.cfg.endpoint_url.rstrip("/")
        else:
            self.endpoint = f"https://s3.{self.cfg.region}.amazonaws.com"

    def _do(self, fn):
        return with_retries(fn, self.cfg.max_retries, self.cfg.retry_initial_backoff_ms)

    def _url(self, bucket: str, key: str = "", query: str = "") -> Tuple[str, str, str]:
        host = urllib.parse.urlparse(self.endpoint).netloc
        uri = f"/{bucket}" + (f"/{urllib.parse.quote(key)}" if key else "")
        url = self.endpoint + uri + (f"?{query}" if query else "")
        return url, host, uri

    split = staticmethod(_split_bucket)

    def get(self, path: str, range: Optional[Tuple[int, int]] = None) -> bytes:
        bucket, key = self.split(path)
        url, host, uri = self._url(bucket, key)

        def go():
            headers = _sigv4_headers(self.cfg, "GET", host, uri, "", b"")
            if range is not None:
                headers["Range"] = f"bytes={range[0]}-{range[1] - 1}"
            _s, _h, body = _http_request(url, headers=headers)
            return body

        return self._do(go)

    def get_size(self, path: str) -> int:
        bucket, key = self.split(path)
        url, host, uri = self._url(bucket, key)

        def go():
            headers = _sigv4_headers(self.cfg, "HEAD", host, uri, "", b"")
            _s, h, _b = _http_request(url, method="HEAD", headers=headers)
            return int(h.get("Content-Length", 0))

        return self._do(go)

    def put(self, path: str, data: bytes) -> None:
        bucket, key = self.split(path)
        url, host, uri = self._url(bucket, key)

        def go():
            headers = _sigv4_headers(self.cfg, "PUT", host, uri, "", data)
            headers["Content-Length"] = str(len(data))
            _http_request(url, method="PUT", headers=headers, data=data)

        self._do(go)

    def delete(self, path: str) -> None:
        bucket, key = self.split(path)
        url, host, uri = self._url(bucket, key)

        def go():
            headers = _sigv4_headers(self.cfg, "DELETE", host, uri, "", b"")
            _http_request(url, method="DELETE", headers=headers)

        self._do(go)

    def ls(self, prefix: str) -> List[str]:
        bucket, key_prefix = self.split(prefix)
        out: List[str] = []
        token: Optional[str] = None
        while True:
            q = {"list-type": "2", "prefix": key_prefix, "max-keys": "1000"}
            if token:
                q["continuation-token"] = token
            # AWS SigV4 canonicalization: percent-encode with %20 (never '+')
            query = "&".join(
                f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
                for k, v in sorted(q.items()))
            url, host, uri = self._url(bucket, query=query)

            def go():
                headers = _sigv4_headers(self.cfg, "GET", host, uri, query, b"")
                _s, _h, body = _http_request(url, headers=headers)
                return body

            body = self._do(go)
            root = ET.fromstring(body)
            ns = root.tag.split("}")[0] + "}" if "}" in root.tag else ""
            for c in root.findall(f"{ns}Contents"):
                k = c.find(f"{ns}Key")
                if k is not None and k.text:
                    out.append(f"{bucket}/{k.text}")
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is not None and trunc.text == "true":
                nt = root.find(f"{ns}NextContinuationToken")
                token = nt.text if nt is not None else None
                if token is None:
                    break
            else:
                break
        return out

    def glob(self, pattern: str) -> List[str]:
        return _prefix_glob(self.ls, pattern)


def _glob_to_regex(pattern: str):
    """Filesystem-style glob: `**` crosses path separators, `*`/`?` do not."""
    import re

    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
                continue
            out.append("[^/]*")
        elif c == "?":
            out.append("[^/]")
        elif c == "[":
            j = pattern.find("]", i)
            if j == -1:
                out.append(re.escape(c))
            else:
                cls = pattern[i + 1:j]
                if cls.startswith("!"):  # glob negation -> regex negation
                    cls = "^" + cls[1:]
                elif cls.startswith("^"):
                    cls = "\\^" + cls[1:]
                out.append("[" + cls + "]")
                i = j + 1
                continue
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("".join(out) + r"\Z")


# ---------------------------------------------------------------------------
# failure injection (reference: daft-io mock.rs)
# ---------------------------------------------------------------------------


class MockSource(ObjectSource):
    """Wraps another source, failing the first N calls per op with a chosen
    error type — drives retry/failure tests without a network."""

    def __init__(self, inner: ObjectSource, fail_first: int = 0,
                 error: Exception = None):
        self.inner = inner
        self.fail_first = fail_first
        self.error = error or TransientError("injected")
        self.calls = 0

    def _maybe_fail(self):
        self.calls += 1
        if self.calls <= self.fail_first:
            raise self.error

    def get(self, path, range=None):
        self._maybe_fail()
        return self.inner.get(path, range)

    def get_size(self, path):
        self._maybe_fail()
        return self.inner.get_size(path)

    def glob(self, pattern):
        self._maybe_fail()
        return self.inner.glob(pattern)

    def ls(self, prefix):
        self._maybe_fail()
        return self.inner.ls(prefix)

    def put(self, path, data):
        self._maybe_fail()
        return self.inner.put(path, data)

    def delete(self, path):
        self._maybe_fail()
        return self.inner.delete(path)




# ---------------------------------------------------------------------------
# Google Cloud Storage (JSON API over stdlib urllib)
# ---------------------------------------------------------------------------


class GCSSource(ObjectSource):
    """GCS over the JSON API (reference: src/daft-io/src/google_cloud.rs).
    Paths are "bucket/key". Download = objects.get?alt=media; listing =
    objects.list with prefix + page tokens. Works against fake-gcs-server
    mocks via GCSConfig.endpoint_url."""

    def __init__(self, config: Optional[IOConfig] = None):
        self.cfg = (config or io_config()).gcs
        self.endpoint = (self.cfg.endpoint_url or
                         "https://storage.googleapis.com").rstrip("/")

    def _do(self, fn):
        return with_retries(fn, self.cfg.max_retries, self.cfg.retry_initial_backoff_ms)

    def _headers(self, range: Optional[Tuple[int, int]] = None) -> dict:
        h = {}
        if self.cfg.token and not self.cfg.anonymous:
            h["Authorization"] = f"Bearer {self.cfg.token}"
        if range is not None:
            h["Range"] = f"bytes={range[0]}-{range[1] - 1}"
        return h

    split = staticmethod(_split_bucket)

    def _obj_url(self, bucket: str, key: str, query: str = "") -> str:
        return (f"{self.endpoint}/storage/v1/b/{urllib.parse.quote(bucket)}"
                f"/o/{urllib.parse.quote(key, safe='')}" + (f"?{query}" if query else ""))

    def get(self, path: str, range: Optional[Tuple[int, int]] = None) -> bytes:
        bucket, key = self.split(path)
        url = self._obj_url(bucket, key, "alt=media")
        _s, _h, body = self._do(lambda: _http_request(url, headers=self._headers(range)))
        return body

    def get_size(self, path: str) -> int:
        import json as _json

        bucket, key = self.split(path)
        url = self._obj_url(bucket, key)
        _s, _h, body = self._do(lambda: _http_request(url, headers=self._headers()))
        return int(_json.loads(body)["size"])

    def ls(self, prefix: str) -> List[str]:
        import json as _json

        bucket, key_prefix = self.split(prefix)
        out: List[str] = []
        token: Optional[str] = None
        while True:
            q = {"prefix": key_prefix, "maxResults": "1000"}
            if token:
                q["pageToken"] = token
            query = urllib.parse.urlencode(q)
            url = (f"{self.endpoint}/storage/v1/b/{urllib.parse.quote(bucket)}/o"
                   f"?{query}")
            _s, _h, body = self._do(lambda u=url: _http_request(u, headers=self._headers()))
            doc = _json.loads(body)
            for item in doc.get("items", []):
                out.append(f"{bucket}/{item['name']}")
            token = doc.get("nextPageToken")
            if not token:
                return sorted(out)

    def glob(self, pattern: str) -> List[str]:
        return _prefix_glob(self.ls, pattern)


# ---------------------------------------------------------------------------
# Azure Blob Storage (REST; SAS or anonymous auth)
# ---------------------------------------------------------------------------


class AzureBlobSource(ObjectSource):
    """Azure Blob over REST (reference: src/daft-io/src/azure_blob.rs). Paths
    are "container/blob". Auth: SAS token appended to every URL, or anonymous
    (public containers / Azurite). Listing = List Blobs XML with prefix."""

    def __init__(self, config: Optional[IOConfig] = None,
                 account: Optional[str] = None):
        self.cfg = (config or io_config()).azure
        account = account or self.cfg.storage_account
        if self.cfg.endpoint_url:
            self.endpoint = self.cfg.endpoint_url.rstrip("/")
        elif account:
            self.endpoint = f"https://{account}.blob.core.windows.net"
        else:
            raise ObjectSourceError(
                "azure: set AZURE_STORAGE_ACCOUNT or AzureConfig.endpoint_url")

    split = staticmethod(_split_bucket)

    def _do(self, fn):
        return with_retries(fn, self.cfg.max_retries, self.cfg.retry_initial_backoff_ms)

    def _with_sas(self, url: str) -> str:
        sas = (self.cfg.sas_token or "").lstrip("?")
        if not sas or self.cfg.anonymous:
            return url
        return url + ("&" if "?" in url else "?") + sas

    def get(self, path: str, range: Optional[Tuple[int, int]] = None) -> bytes:
        container, blob = self.split(path)
        url = self._with_sas(f"{self.endpoint}/{container}/{urllib.parse.quote(blob)}")
        headers = {"x-ms-version": "2021-08-06"}
        if range is not None:
            headers["Range"] = f"bytes={range[0]}-{range[1] - 1}"
        _s, _h, body = self._do(lambda: _http_request(url, headers=headers))
        return body

    def get_size(self, path: str) -> int:
        container, blob = self.split(path)
        url = self._with_sas(f"{self.endpoint}/{container}/{urllib.parse.quote(blob)}")
        _s, h, _b = self._do(lambda: _http_request(
            url, method="HEAD", headers={"x-ms-version": "2021-08-06"}))
        cl = h.get("Content-Length")
        if cl is None:
            raise ObjectSourceError(f"{path}: no Content-Length")
        return int(cl)

    def ls(self, prefix: str) -> List[str]:
        container, blob_prefix = self.split(prefix)
        out: List[str] = []
        marker = ""
        while True:
            q = {"restype": "container", "comp": "list", "prefix": blob_prefix}
            if marker:
                q["marker"] = marker
            url = self._with_sas(
                f"{self.endpoint}/{container}?{urllib.parse.urlencode(q)}")
            _s, _h, body = self._do(lambda u=url: _http_request(
                u, headers={"x-ms-version": "2021-08-06"}))
            root = ET.fromstring(body)
            for name in root.iter("Name"):
                if name.text:
                    out.append(f"{container}/{name.text}")
            nm = root.find("NextMarker")
            marker = nm.text if nm is not None and nm.text else ""
            if not marker:
                return sorted(out)

    def glob(self, pattern: str) -> List[str]:
        return _prefix_glob(self.ls, pattern)


# ---------------------------------------------------------------------------
# resolution
# ---------------------------------------------------------------------------


def resolve_source(path: str, config: Optional[IOConfig] = None
                   ) -> Tuple[ObjectSource, str]:
    """Map a user path to (source, source-relative path)."""
    if path.startswith("s3://") or path.startswith("s3a://"):
        rest = path.split("://", 1)[1]
        return S3Source(config), rest
    if path.startswith("gs://") or path.startswith("gcs://"):
        return GCSSource(config), path.split("://", 1)[1]
    if path.startswith("az://"):
        return AzureBlobSource(config), path.split("://", 1)[1]
    if path.startswith(("abfs://", "abfss://")):
        # abfs(s)://container@account.dfs.core.windows.net/path
        rest = path.split("://", 1)[1]
        authority, _, blob_path = rest.partition("/")
        if "@" in authority:
            container, host = authority.split("@", 1)
            account = host.split(".", 1)[0]
            return (AzureBlobSource(config, account=account),
                    f"{container}/{blob_path}")
        return AzureBlobSource(config), rest
    if path.startswith("hf://"):
        # HuggingFace Hub: hf://datasets/{repo}/{path} resolves to the public
        # CDN URL (reference: src/daft-io/src/huggingface.rs path mapping)
        rest = path[len("hf://"):]
        parts = rest.split("/")
        if parts and parts[0] in ("datasets", "spaces", "models"):
            kind = parts[0]
            repo = "/".join(parts[1:3])
            file_path = "/".join(parts[3:])
        else:
            kind, repo, file_path = "models", "/".join(parts[:2]), "/".join(parts[2:])
        if any(ch in rest for ch in "*?["):
            raise ObjectSourceError(
                "hf:// paths do not support globs; name the file explicitly")
        base = os.environ.get("DAFT_TPU_HF_ENDPOINT", "https://huggingface.co")
        prefix = "" if kind == "models" else f"{kind}/"
        quoted = "/".join(urllib.parse.quote(seg) for seg in file_path.split("/"))
        return HTTPSource(config), f"{base}/{prefix}{repo}/resolve/main/{quoted}"
    if path.startswith("http://") or path.startswith("https://"):
        return HTTPSource(config), path
    if path.startswith("file://"):
        return LocalSource(), path[len("file://"):]
    return LocalSource(), path


def is_remote(path: str) -> bool:
    return path.startswith(("s3://", "s3a://", "gs://", "gcs://", "az://",
                            "abfs://", "abfss://", "hf://", "http://", "https://"))


def expand_remote(path: str, config: Optional[IOConfig] = None,
                  extensions: Tuple[str, ...] = ()) -> List[str]:
    """Glob/list a remote path, returning full scheme-qualified paths.

    A non-glob path naming a "directory" prefix lists its objects (mirroring
    the local-path directory walk), so write -> read round-trips work."""
    source, rel = resolve_source(path, config)
    scheme = path.split("://", 1)[0] + "://"
    if isinstance(source, HTTPSource):
        return [path]
    if any(ch in rel for ch in "*?["):
        return [scheme + p for p in source.glob(rel)]
    listed = source.ls(rel.rstrip("/") + "/")
    if listed:
        return [scheme + p for p in listed
                if not extensions or p.endswith(tuple(extensions))]
    return [path]


class RangedObjectFile:
    """Random-access file view over a remote object: fetches byte ranges on
    demand with readahead, so parquet column pruning only downloads the byte
    ranges it touches (reference: daft-parquet read_planner.rs range
    coalescing). Implements the file protocol pyarrow needs (read/seek/tell).
    """

    _READAHEAD = 1 << 20  # 1MB

    def __init__(self, source: ObjectSource, path: str, size: Optional[int] = None):
        self.source = source
        self.path = path
        self._size = size if size is not None else source.get_size(path)
        self._pos = 0
        self._closed = False
        self._cache: List[Tuple[int, bytes]] = []  # (start, data) fetched chunks

    # -- python file protocol (what pyarrow PythonFile uses) --------------------
    def size(self) -> int:
        return self._size

    def tell(self) -> int:
        return self._pos

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return self._pos

    def seekable(self) -> bool:
        return True

    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    @property
    def closed(self) -> bool:  # pyarrow probes this as an attribute
        return self._closed

    def close(self) -> None:
        self._closed = True
        self._cache.clear()

    def flush(self) -> None:
        pass

    def _fetch(self, start: int, end: int) -> bytes:
        for cs, data in self._cache:
            if cs <= start and end <= cs + len(data):
                return data[start - cs:end - cs]
        fetch_end = min(self._size, max(end, start + self._READAHEAD))
        data = self.source.get(self.path, (start, fetch_end))
        self._cache.append((start, data))
        # small LRU: sequential consumers never re-hit old chunks, so holding
        # more than a few readahead windows just pins dead memory
        if len(self._cache) > 4:
            self._cache.pop(0)
        return data[: end - start]

    def read(self, nbytes: int = -1) -> bytes:
        if nbytes is None or nbytes < 0:
            nbytes = self._size - self._pos
        end = min(self._size, self._pos + nbytes)
        if end <= self._pos:
            return b""
        out = self._fetch(self._pos, end)
        self._pos = end
        return out


_HTTP_BODY_CACHE: "dict[str, Tuple[bytes, float]]" = {}
# concurrent queries (serving tier) share this module-level cache
_HTTP_BODY_CACHE_LOCK = threading.Lock()


def open_input(path: str, config: Optional[IOConfig] = None):
    """Open a path for pyarrow consumption: local paths pass through (pyarrow
    memory-maps them), remote objects return a ranged-read file object."""
    import pyarrow as pa

    if not is_remote(path):
        return path
    source, rel = resolve_source(path, config)
    if isinstance(source, HTTPSource):
        # no reliable ranged reads on arbitrary HTTP servers: buffer fully.
        # A tiny TTL'd body cache stops schema inference + row-count estimation
        # + the actual scan from downloading the same file repeatedly within
        # one query, without serving stale bytes across sessions.
        with _HTTP_BODY_CACHE_LOCK:
            entry = _HTTP_BODY_CACHE.get(path)
        if entry is not None and time.time() - entry[1] < 60.0:
            body = entry[0]
        else:
            body = source.get(rel)  # downloaded outside the lock
            with _HTTP_BODY_CACHE_LOCK:
                _HTTP_BODY_CACHE[path] = (body, time.time())
                while len(_HTTP_BODY_CACHE) > 2:
                    _HTTP_BODY_CACHE.pop(next(iter(_HTTP_BODY_CACHE)))
        return pa.BufferReader(body)
    return pa.PythonFile(RangedObjectFile(source, rel), mode="r")
