from .scan import Pushdowns, ScanOperator, ScanTask

__all__ = ["Pushdowns", "ScanOperator", "ScanTask"]
