"""Path expansion: globs, directories, lists (reference: daft-io object_store_glob.rs,
local-filesystem subset; object stores land with the native IO milestone)."""

from __future__ import annotations

import glob as _glob
import os
from typing import List, Sequence, Union


def expand_paths(path: Union[str, List[str]], extensions: Sequence[str] = ()) -> List[str]:
    from .object_store import expand_remote, is_remote

    paths = [path] if isinstance(path, str) else list(path)
    out: List[str] = []
    for p in paths:
        if is_remote(p):
            out.extend(expand_remote(p, extensions=tuple(extensions)))
            continue
        if p.startswith("file://"):
            p = p[len("file://"):]
        if any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p, recursive=True)))
        elif os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if not extensions or f.endswith(tuple(extensions)):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    # de-dup, preserve order
    seen = set()
    uniq = []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq
