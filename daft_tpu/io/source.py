"""Custom Python data sources.

Reference parity: daft/io/source.py:26,74 — DataSource/DataSourceTask ABCs let
users plug arbitrary systems (databases, APIs, queues) into the engine as
first-class scans with pushdown visibility; tasks are independently
executable units the engine parallelizes and ships to workers.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Iterator, List, Optional

from ..core.micropartition import MicroPartition
from ..schema import Schema
from .scan import Pushdowns, ScanOperator, ScanTask


class DataSourceTask(ABC):
    """One independently-readable slice of a DataSource."""

    @property
    @abstractmethod
    def schema(self) -> Schema:
        ...

    @abstractmethod
    def read(self) -> Iterator[MicroPartition]:
        """Yield the task's data as MicroPartitions."""
        ...

    def size_bytes(self) -> Optional[int]:
        return None


class DataSource(ABC):
    """A user-defined source of DataFrames.

    Implement name/schema/get_tasks; call .read() for a lazy DataFrame. The
    engine attaches Pushdowns (column pruning / filters / limit) — tasks may
    exploit them or ignore them (the executor re-applies semantics it can't
    verify were applied).
    """

    @property
    @abstractmethod
    def name(self) -> str:
        ...

    @property
    @abstractmethod
    def schema(self) -> Schema:
        ...

    @abstractmethod
    def get_tasks(self, pushdowns: Pushdowns) -> Iterator[DataSourceTask]:
        ...

    def read(self):
        from ..dataframe import DataFrame
        from ..plan.builder import LogicalPlanBuilder

        return DataFrame(LogicalPlanBuilder.from_scan(_DataSourceScanOperator(self)))


class _DataSourceScanOperator(ScanOperator):
    """Adapter: DataSource -> the engine's ScanOperator contract."""

    def __init__(self, source: DataSource):
        self._source = source

    def name(self) -> str:
        return f"DataSource({self._source.name})"

    def schema(self) -> Schema:
        return self._source.schema

    # accept every pushdown as a HINT: tasks may exploit them, and the engine
    # re-applies anything the task didn't verify (filters_applied=False below)
    def can_absorb_filter(self) -> bool:
        return True

    def can_absorb_limit(self) -> bool:
        return True

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        out = []
        for task in self._source.get_tasks(pushdowns):
            out.append(ScanTask(
                read=task.read,
                schema=task.schema,
                size_bytes=task.size_bytes(),
                # conservatively assume the task ignored the pushdowns; the
                # executor re-filters / re-limits
                filters_applied=False,
                limit_applied=False,
                source_label=self._source.name,
            ))
        return out
