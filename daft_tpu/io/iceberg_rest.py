"""Iceberg REST catalog client (spec: the `rest-catalog-open-api.yaml` wire
protocol; reference counterpart: daft/catalog/__iceberg.py IcebergCatalog over
pyiceberg's RestCatalog — implemented here directly against the HTTP API, no
pyiceberg).

Supported surface:
    cat = IcebergRestCatalog("http://host:8181", warehouse="wh")
    cat.list_namespaces()                  -> ["sales", ...]
    cat.create_namespace("sales")
    cat.list_tables("sales")               -> ["sales.orders", ...]
    df = cat.load_table("sales.orders")    # snapshot read via metadata JSON
    cat.write_table("sales.orders", df)    # create/append + REST commit

Auth: pass `token` (Bearer) or `credential` ("client_id:client_secret" — one
OAuth2 client-credentials exchange against {uri}/v1/oauth/tokens). Session
integration: Session.attach_catalog(cat, "ice") then
`sql("SELECT ... FROM ice.sales.orders")`.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional


class IcebergRestError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"iceberg rest error {status}: {body[:200]}")
        self.status = status


class IcebergRestCatalog:
    def __init__(self, uri: str, name: str = "rest",
                 warehouse: Optional[str] = None,
                 token: Optional[str] = None,
                 credential: Optional[str] = None,
                 timeout: float = 30.0):
        self.uri = uri.rstrip("/")
        self.name = name
        self.timeout = timeout
        self._token = token
        if credential is not None and token is None:
            self._token = self._oauth(credential)
        # GET /v1/config: server defaults/overrides (prefix, warehouse)
        q = f"?warehouse={urllib.parse.quote(warehouse)}" if warehouse else ""
        cfg = self._request("GET", f"/v1/config{q}")
        merged: Dict[str, Any] = dict(cfg.get("defaults") or {})
        merged.update(cfg.get("overrides") or {})
        self.properties = merged
        prefix = merged.get("prefix", "")
        self._prefix = f"/{prefix.strip('/')}" if prefix else ""

    # ---- wire ----------------------------------------------------------------------
    def _oauth(self, credential: str) -> str:
        cid, _, secret = credential.partition(":")
        body = urllib.parse.urlencode({
            "grant_type": "client_credentials",
            "client_id": cid, "client_secret": secret,
            "scope": "catalog"}).encode()
        req = urllib.request.Request(
            f"{self.uri}/v1/oauth/tokens", data=body,
            headers={"Content-Type": "application/x-www-form-urlencoded"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())["access_token"]

    def _request(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        url = f"{self.uri}{path}"
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read()
                return json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            raise IcebergRestError(e.code, e.read().decode("utf-8", "replace")) \
                from None

    def _ns_path(self, namespace: str) -> str:
        # multipart namespaces join with the %1F unit separator per spec
        return urllib.parse.quote("\x1f".join(namespace.split(".")), safe="")

    # ---- namespaces ----------------------------------------------------------------
    def list_namespaces(self) -> List[str]:
        out = self._request("GET", f"{self._prefix}/v1/namespaces")
        return [".".join(ns) for ns in out.get("namespaces", [])]

    def create_namespace(self, namespace: str,
                         properties: Optional[dict] = None) -> None:
        self._request("POST", f"{self._prefix}/v1/namespaces",
                      {"namespace": namespace.split("."),
                       "properties": properties or {}})

    def drop_namespace(self, namespace: str) -> None:
        self._request("DELETE",
                      f"{self._prefix}/v1/namespaces/{self._ns_path(namespace)}")

    # ---- tables --------------------------------------------------------------------
    def _split(self, name: str):
        parts = name.split(".")
        if len(parts) < 2:
            raise ValueError(
                f"table name {name!r} must be namespace-qualified (ns.table)")
        return ".".join(parts[:-1]), parts[-1]

    def list_tables(self, namespace: Optional[str] = None,
                    pattern: Optional[str] = None) -> List[str]:
        spaces = [namespace] if namespace else self.list_namespaces()
        out: List[str] = []
        for ns in spaces:
            r = self._request(
                "GET", f"{self._prefix}/v1/namespaces/{self._ns_path(ns)}/tables")
            for ident in r.get("identifiers", []):
                full = ".".join(ident["namespace"] + [ident["name"]])
                if pattern is None or pattern in full:
                    out.append(full)
        return sorted(out)

    def _load(self, name: str) -> dict:
        ns, table = self._split(name)
        return self._request(
            "GET",
            f"{self._prefix}/v1/namespaces/{self._ns_path(ns)}/tables/"
            f"{urllib.parse.quote(table)}")

    def table_metadata(self, name: str) -> dict:
        return self._load(name)["metadata"]

    def load_table(self, name: str, snapshot_id: Optional[int] = None):
        """DataFrame over the table's current (or given) snapshot: the REST
        response carries the full metadata JSON; manifests/data files read
        from the metadata location."""
        from ..dataframe import DataFrame
        from ..plan.builder import LogicalPlanBuilder
        from .iceberg import IcebergScanOperator

        meta = self.table_metadata(name)
        location = self._local_location(meta.get("location", ""))
        op = IcebergScanOperator(location, snapshot_id=snapshot_id, meta=meta)
        return DataFrame(LogicalPlanBuilder.from_scan(op))

    @staticmethod
    def _local_location(location: str) -> str:
        return location[len("file://"):] if location.startswith("file://") \
            else location

    def create_table(self, name: str, schema) -> dict:
        """CREATE TABLE with an Iceberg-encoded schema; returns metadata."""
        from .iceberg import _dtype_to_icetype

        ns, table = self._split(name)
        fields = [{"id": i + 1, "name": f.name, "required": False,
                   "type": _dtype_to_icetype(f.dtype)}
                  for i, f in enumerate(schema)]
        body = {"name": table,
                "schema": {"type": "struct", "schema-id": 0, "fields": fields}}
        return self._request(
            "POST",
            f"{self._prefix}/v1/namespaces/{self._ns_path(ns)}/tables", body)

    def drop_table(self, name: str) -> None:
        ns, table = self._split(name)
        self._request(
            "DELETE",
            f"{self._prefix}/v1/namespaces/{self._ns_path(ns)}/tables/"
            f"{urllib.parse.quote(table)}")

    def write_table(self, name: str, df, mode: str = "append"):
        """Write data files + manifests under the table location, then COMMIT
        the new snapshot through the REST transaction endpoint (add-snapshot +
        set-snapshot-ref updates with an assert-ref requirement, the spec's
        optimistic-concurrency handshake)."""
        try:
            loaded = self._load(name)
        except IcebergRestError as e:
            if e.status != 404:
                raise
            self.create_table(name, df.schema)
            loaded = self._load(name)
        meta = loaded["metadata"]
        location = self._local_location(meta.get("location", ""))

        from .iceberg import write_iceberg

        # stage data + manifests + a local metadata version under the table
        # location (the same layout write_iceberg produces), then surface the
        # NEW snapshot to the catalog service
        os.makedirs(location, exist_ok=True)
        result = write_iceberg(df, location, mode=mode)
        from .iceberg import _load_table_metadata

        staged = _load_table_metadata(location)
        snap = next(s for s in staged["snapshots"]
                    if s["snapshot-id"] == staged.get("current-snapshot-id"))

        ns, table = self._split(name)
        base_ref = (meta.get("refs") or {}).get("main")
        requirements = [{"type": "assert-ref-snapshot-id", "ref": "main",
                         "snapshot-id": base_ref.get("snapshot-id")
                         if base_ref else None}]
        updates = [
            {"action": "add-snapshot", "snapshot": snap},
            {"action": "set-snapshot-ref", "ref-name": "main",
             "type": "branch", "snapshot-id": snap["snapshot-id"]},
        ]
        self._request(
            "POST",
            f"{self._prefix}/v1/namespaces/{self._ns_path(ns)}/tables/"
            f"{urllib.parse.quote(table)}",
            {"requirements": requirements, "updates": updates})
        return result


def make_mock_rest_server(warehouse_root: str):
    """In-process Iceberg REST catalog service over a local warehouse dir —
    the test double (same pattern as the S3/GCS mocks in tests/): implements
    config, oauth, namespace CRUD, table list/create/load, and commit with
    assert-ref optimistic concurrency. Returns (server, base_uri); caller
    must server.shutdown()."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    state = {
        "namespaces": {},   # ns tuple -> properties
        "tables": {},       # (ns tuple, name) -> metadata dict
    }
    lock = threading.Lock()

    def ns_of(seg: str):
        return tuple(urllib.parse.unquote(seg).split("\x1f"))

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, body: Optional[dict] = None):
            data = json.dumps(body or {}).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n) if n else b""
            if not raw:
                return {}
            if self.headers.get("Content-Type", "").startswith(
                    "application/x-www-form-urlencoded"):
                return dict(urllib.parse.parse_qsl(raw.decode()))
            return json.loads(raw)

        def do_GET(self):
            parts = self.path.split("?")[0].strip("/").split("/")
            with lock:
                if parts[:2] == ["v1", "config"]:
                    return self._send(200, {"defaults": {}, "overrides": {}})
                if parts[:2] == ["v1", "namespaces"] and len(parts) == 2:
                    return self._send(200, {"namespaces": [
                        list(ns) for ns in sorted(state["namespaces"])]})
                if len(parts) == 4 and parts[3] == "tables":
                    ns = ns_of(parts[2])
                    idents = [{"namespace": list(n), "name": t}
                              for (n, t) in sorted(state["tables"]) if n == ns]
                    return self._send(200, {"identifiers": idents})
                if len(parts) == 5 and parts[3] == "tables":
                    key = (ns_of(parts[2]), urllib.parse.unquote(parts[4]))
                    meta = state["tables"].get(key)
                    if meta is None:
                        return self._send(404, {"error": {
                            "message": "table not found", "code": 404}})
                    return self._send(200, {
                        "metadata-location": meta["location"] + "/metadata",
                        "metadata": meta})
            self._send(404, {})

        def do_POST(self):
            parts = self.path.split("?")[0].strip("/").split("/")
            body = self._body()
            with lock:
                if parts[:3] == ["v1", "oauth", "tokens"]:
                    if body.get("client_id") != "user" \
                            or body.get("client_secret") != "pass":
                        return self._send(401, {"error": {
                            "message": "bad credential", "code": 401}})
                    return self._send(200, {"access_token": "mock-token",
                                            "token_type": "bearer"})
                # everything below requires auth when a token was issued
                if parts[:2] == ["v1", "namespaces"] and len(parts) == 2:
                    ns = tuple(body["namespace"])
                    state["namespaces"][ns] = body.get("properties", {})
                    return self._send(200, {"namespace": list(ns),
                                            "properties": {}})
                if len(parts) == 4 and parts[3] == "tables":
                    ns = ns_of(parts[2])
                    if ns not in state["namespaces"]:
                        return self._send(404, {"error": {
                            "message": "namespace not found", "code": 404}})
                    tname = body["name"]
                    loc = os.path.join(warehouse_root, *ns, tname)
                    os.makedirs(loc, exist_ok=True)
                    now = int(time.time() * 1000)
                    meta = {
                        "format-version": 2, "table-uuid": f"uuid-{ns}-{tname}",
                        "location": loc, "last-sequence-number": 0,
                        "last-updated-ms": now,
                        "last-column-id": len(body["schema"]["fields"]),
                        "schemas": [body["schema"]], "current-schema-id": 0,
                        "partition-specs": [{"spec-id": 0, "fields": []}],
                        "default-spec-id": 0, "last-partition-id": 999,
                        "sort-orders": [{"order-id": 0, "fields": []}],
                        "default-sort-order-id": 0, "properties": {},
                        "snapshots": [], "refs": {},
                        "snapshot-log": [], "metadata-log": [],
                    }
                    state["tables"][(ns, tname)] = meta
                    return self._send(200, {"metadata-location": loc,
                                            "metadata": meta})
                if len(parts) == 5 and parts[3] == "tables":
                    key = (ns_of(parts[2]), urllib.parse.unquote(parts[4]))
                    meta = state["tables"].get(key)
                    if meta is None:
                        return self._send(404, {"error": {
                            "message": "table not found", "code": 404}})
                    for req in body.get("requirements", []):
                        if req.get("type") == "assert-ref-snapshot-id":
                            ref = (meta.get("refs") or {}).get(
                                req.get("ref", "main"))
                            have = ref.get("snapshot-id") if ref else None
                            if have != req.get("snapshot-id"):
                                return self._send(409, {"error": {
                                    "message": "ref mismatch", "code": 409}})
                    for upd in body.get("updates", []):
                        if upd["action"] == "add-snapshot":
                            meta.setdefault("snapshots", []).append(
                                upd["snapshot"])
                        elif upd["action"] == "set-snapshot-ref":
                            meta.setdefault("refs", {})[upd["ref-name"]] = {
                                "snapshot-id": upd["snapshot-id"],
                                "type": upd.get("type", "branch")}
                            meta["current-snapshot-id"] = upd["snapshot-id"]
                    return self._send(200, {"metadata-location": meta["location"],
                                            "metadata": meta})
            self._send(404, {})

        def do_DELETE(self):
            parts = self.path.strip("/").split("/")
            with lock:
                if parts[:2] == ["v1", "namespaces"] and len(parts) == 3:
                    state["namespaces"].pop(ns_of(parts[2]), None)
                    return self._send(204)
                if len(parts) == 5 and parts[3] == "tables":
                    key = (ns_of(parts[2]), urllib.parse.unquote(parts[4]))
                    state["tables"].pop(key, None)
                    return self._send(204)
            self._send(404, {})

    server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"
