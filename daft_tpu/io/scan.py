"""Scan planning abstractions.

Reference parity: src/daft-scan/src/scan_operator.rs:12 (ScanOperator trait),
src/daft-scan/src/lib.rs:346 (ScanTask), src/daft-scan/src/pushdowns.rs (Pushdowns).

A ScanOperator describes an external data source; the optimizer attaches Pushdowns
(column pruning, predicate, limit) and physical translation materializes ScanTasks —
each an independently-executable unit reading some files/byte-ranges and yielding
MicroPartitions.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, List, Optional

from ..expressions import Expression
from ..schema import Schema


@dataclasses.dataclass
class Pushdowns:
    """Pushed-down hints a scan may exploit (all optional; scans may ignore filters/
    limits as long as they report whether they applied them exactly)."""

    columns: Optional[List[str]] = None
    filters: Optional[Expression] = None
    limit: Optional[int] = None

    def __repr__(self) -> str:
        parts = []
        if self.columns is not None:
            parts.append(f"columns={self.columns}")
        if self.filters is not None:
            parts.append(f"filters={self.filters}")
        if self.limit is not None:
            parts.append(f"limit={self.limit}")
        return "Pushdowns(" + ", ".join(parts) + ")"

    def is_empty(self) -> bool:
        return self.columns is None and self.filters is None and self.limit is None


@dataclasses.dataclass
class ScanTask:
    """One unit of scan work: a closure producing MicroPartitions plus metadata for
    scheduling/stats (reference ScanTask carries sources+pushdowns+size estimates)."""

    read: Callable[[], Iterator[Any]]  # yields MicroPartition
    schema: Schema
    size_bytes: Optional[int] = None
    num_rows: Optional[int] = None
    # True when the reader already applied the pushdown exactly (so the executor can
    # skip re-filtering / re-limiting).
    filters_applied: bool = False
    limit_applied: bool = False
    source_label: str = ""


class ScanOperator:
    """Base class for external sources (parquet/csv/json readers, Python DataSources)."""

    def name(self) -> str:
        return type(self).__name__

    def schema(self) -> Schema:
        raise NotImplementedError

    def can_absorb_select(self) -> bool:
        return False

    def can_absorb_filter(self) -> bool:
        return False

    def can_absorb_limit(self) -> bool:
        return False

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        raise NotImplementedError

    def approx_num_rows(self, pushdowns: Pushdowns) -> Optional[float]:
        return None
