"""Scan planning abstractions.

Reference parity: src/daft-scan/src/scan_operator.rs:12 (ScanOperator trait),
src/daft-scan/src/lib.rs:346 (ScanTask), src/daft-scan/src/pushdowns.rs (Pushdowns).

A ScanOperator describes an external data source; the optimizer attaches Pushdowns
(column pruning, predicate, limit) and physical translation materializes ScanTasks —
each an independently-executable unit reading some files/byte-ranges and yielding
MicroPartitions.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, List, Optional

from ..expressions import Expression
from ..schema import Schema


@dataclasses.dataclass
class Pushdowns:
    """Pushed-down hints a scan may exploit (all optional; scans may ignore filters/
    limits as long as they report whether they applied them exactly)."""

    columns: Optional[List[str]] = None
    filters: Optional[Expression] = None
    limit: Optional[int] = None

    def __repr__(self) -> str:
        parts = []
        if self.columns is not None:
            parts.append(f"columns={self.columns}")
        if self.filters is not None:
            parts.append(f"filters={self.filters}")
        if self.limit is not None:
            parts.append(f"limit={self.limit}")
        return "Pushdowns(" + ", ".join(parts) + ")"

    def is_empty(self) -> bool:
        return self.columns is None and self.filters is None and self.limit is None


@dataclasses.dataclass
class ScanTask:
    """One unit of scan work: a closure producing MicroPartitions plus metadata for
    scheduling/stats (reference ScanTask carries sources+pushdowns+size estimates)."""

    read: Callable[[], Iterator[Any]]  # yields MicroPartition
    schema: Schema
    size_bytes: Optional[int] = None
    num_rows: Optional[int] = None
    # True when the reader already applied the pushdown exactly (so the executor can
    # skip re-filtering / re-limiting).
    filters_applied: bool = False
    limit_applied: bool = False
    source_label: str = ""


def merge_small_tasks(tasks: List[ScanTask], target_bytes: int) -> List[ScanTask]:
    """Coalesce runs of small adjacent ScanTasks toward `target_bytes` so a
    many-tiny-files source doesn't pay per-task scheduling/IO overhead (the
    merge half of scan split planning; io/parquet.py owns the split half).

    Only tasks with a KNOWN size merge, and only while every merged member
    agrees on filters_applied (a merged task must be re-filterable as one
    unit); limit-absorbing tasks never merge (the limit bookkeeping is
    per-task). Order is preserved — a merged task reads its members
    sequentially, so row order matches the unmerged plan exactly."""
    if target_bytes <= 0 or len(tasks) <= 1:
        return tasks

    out: List[ScanTask] = []
    group: List[ScanTask] = []
    group_bytes = 0

    def flush() -> None:
        nonlocal group, group_bytes
        if not group:
            return
        if len(group) == 1:
            out.append(group[0])
        else:
            members = list(group)

            def read_all(_members=members):
                for t in _members:
                    yield from t.read()

            rows = [t.num_rows for t in members]
            out.append(ScanTask(
                read=read_all,
                schema=members[0].schema,
                size_bytes=sum(t.size_bytes for t in members),
                num_rows=sum(rows) if all(r is not None for r in rows) else None,
                filters_applied=members[0].filters_applied,
                limit_applied=False,
                source_label=f"{members[0].source_label} (+{len(members) - 1} merged)",
            ))
            from ..observability.metrics import registry

            registry().inc("scan_tasks_merged", len(members) - 1)
        group, group_bytes = [], 0

    for t in tasks:
        mergeable = (t.size_bytes is not None and not t.limit_applied
                     and t.size_bytes < target_bytes)
        if not mergeable:
            flush()
            out.append(t)
            continue
        if group and (group_bytes + t.size_bytes > target_bytes
                      or group[0].filters_applied != t.filters_applied
                      or group[0].schema is not t.schema):
            flush()
        group.append(t)
        group_bytes += t.size_bytes
    flush()
    return out


class ScanOperator:
    """Base class for external sources (parquet/csv/json readers, Python DataSources)."""

    def name(self) -> str:
        return type(self).__name__

    def schema(self) -> Schema:
        raise NotImplementedError

    def can_absorb_select(self) -> bool:
        return False

    def can_absorb_filter(self) -> bool:
        return False

    def can_absorb_limit(self) -> bool:
        return False

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        raise NotImplementedError

    def approx_num_rows(self, pushdowns: Pushdowns) -> Optional[float]:
        return None
