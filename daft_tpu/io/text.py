"""Line-oriented text scan (reference parity: src/daft-text — newline-split
reads for LLM/data-prep pipelines). One output column ``text``; supports local
and remote paths plus .gz transparently."""

from __future__ import annotations

import gzip
import io
import os
from typing import List, Optional, Union

from ..core.micropartition import MicroPartition
from ..datatype import DataType, Field
from ..schema import Schema
from .paths import expand_paths
from .scan import Pushdowns, ScanOperator, ScanTask

_LINES_PER_BATCH = 64 * 1024


def _open_text(path: str):
    from .object_store import is_remote, resolve_source

    if is_remote(path):
        source, rel = resolve_source(path)
        raw: io.IOBase = io.BytesIO(source.get(rel))
    else:
        raw = open(path, "rb")
    if path.endswith(".gz"):
        raw = gzip.open(raw, "rb")
    return io.TextIOWrapper(raw, encoding="utf-8", errors="replace")


class TextScanOperator(ScanOperator):
    def __init__(self, path: Union[str, List[str]], **_options):
        self._paths = expand_paths(path)
        if not self._paths:
            raise FileNotFoundError(f"no text files matched {path!r}")
        self._schema = Schema([Field("text", DataType.string())])

    def name(self) -> str:
        return f"TextScan({len(self._paths)} files)"

    def schema(self) -> Schema:
        return self._schema

    def can_absorb_limit(self) -> bool:
        return True

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        schema = self._schema
        limit = pushdowns.limit
        tasks = []
        for path in self._paths:
            def make(path=path):
                def read():
                    produced = 0
                    buf: List[str] = []
                    with _open_text(path) as f:
                        for line in f:
                            if limit is not None and produced >= limit:
                                break
                            buf.append(line.rstrip("\n"))
                            produced += 1
                            if len(buf) >= _LINES_PER_BATCH:
                                yield MicroPartition.from_pydict({"text": buf})
                                buf = []
                    if buf:
                        yield MicroPartition.from_pydict({"text": buf})

                return read

            tasks.append(ScanTask(
                read=make(), schema=schema,
                size_bytes=os.path.getsize(path) if os.path.exists(path) else None,
                limit_applied=False, source_label=path,
            ))
        return tasks
