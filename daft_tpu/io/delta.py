"""Delta Lake table read support (JSON transaction log + parquet checkpoints).

Reference parity: daft/io/delta_lake/delta_lake_scan.py (DeltaLakeScanOperator:
replay the _delta_log, prune files on partition values and add-action stats,
emit per-file scan tasks). The reference uses the deltalake package; here the
protocol is implemented directly: actions are newline-delimited JSON in
_delta_log/NNNN.json, optionally compacted into NNNN.checkpoint.parquet.

Delta data files do NOT contain partition columns — they are reconstructed as
constant columns from each add-action's partitionValues.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ..datatype import DataType, Field
from ..schema import Schema
from .scan import Pushdowns, ScanOperator, ScanTask


def _delta_type_to_dtype(t: Any) -> DataType:
    if isinstance(t, dict):
        kind = t.get("type")
        if kind == "struct":
            return DataType.struct({f["name"]: _delta_type_to_dtype(f["type"])
                                    for f in t["fields"]})
        if kind == "array":
            return DataType.list(_delta_type_to_dtype(t["elementType"]))
        if kind == "map":
            return DataType.map(_delta_type_to_dtype(t["keyType"]),
                                _delta_type_to_dtype(t["valueType"]))
        raise NotImplementedError(f"delta type {t!r}")
    if t.startswith("decimal("):
        p, s = t[len("decimal("):-1].split(",")
        return DataType.decimal128(int(p), int(s))
    simple = {
        "string": DataType.string, "long": DataType.int64, "integer": DataType.int32,
        "short": DataType.int16, "byte": DataType.int8, "float": DataType.float32,
        "double": DataType.float64, "boolean": DataType.bool, "binary": DataType.binary,
        "date": DataType.date,
    }
    if t in simple:
        return simple[t]()
    if t == "timestamp":
        return DataType.timestamp("us", "UTC")
    raise NotImplementedError(f"delta type {t!r}")


def _parse_partition_value(raw: Optional[str], dtype: DataType) -> Any:
    """Delta stores partition values as strings; decode to the column dtype."""
    if raw is None:
        return None
    if dtype.is_integer():
        return int(raw)
    if dtype.is_floating():
        return float(raw)
    if dtype.is_boolean():
        return raw.lower() == "true"
    if dtype == DataType.date():
        import datetime

        return datetime.date.fromisoformat(raw)
    return raw


class _TableState:
    def __init__(self):
        self.schema_raw: Optional[dict] = None
        self.partition_columns: List[str] = []
        self.files: Dict[str, dict] = {}  # path -> add action

    def apply(self, action: dict) -> None:
        if "metaData" in action:
            md = action["metaData"]
            self.schema_raw = json.loads(md["schemaString"])
            self.partition_columns = md.get("partitionColumns", [])
        elif "add" in action:
            add = dict(action["add"])
            pv = add.get("partitionValues")
            if isinstance(pv, list):  # arrow MAP columns decode to [(k, v), ...]
                add["partitionValues"] = dict(pv)
            self.files[add["path"]] = add
        elif "remove" in action:
            self.files.pop(action["remove"]["path"], None)
        elif "protocol" in action:
            p = action["protocol"]
            if p.get("minReaderVersion", 1) > 2:
                raise NotImplementedError(
                    f"delta minReaderVersion {p['minReaderVersion']} > 2")


def _replay_log(table_path: str) -> _TableState:
    log_dir = os.path.join(table_path, "_delta_log")
    if not os.path.isdir(log_dir):
        raise FileNotFoundError(f"not a delta table (no _delta_log/): {table_path}")
    state = _TableState()
    names = os.listdir(log_dir)
    # single-part NNNN.checkpoint.parquet and multi-part
    # NNNN.checkpoint.<part>.<numparts>.parquet both count
    import re as _re

    cp_pat = _re.compile(r"^(\d+)\.checkpoint(?:\.\d+\.\d+)?\.parquet$")
    by_version: Dict[int, List[str]] = {}
    for n in names:
        m = cp_pat.match(n)
        if m:
            by_version.setdefault(int(m.group(1)), []).append(n)
    start_version = -1
    if by_version:
        start_version = max(by_version)
        import pyarrow.parquet as pq

        for cp in sorted(by_version[start_version]):
            table = pq.read_table(os.path.join(log_dir, cp))
            for row in table.to_pylist():
                for key in ("metaData", "add", "remove", "protocol"):
                    if row.get(key) is not None:
                        state.apply({key: row[key]})
    versions = sorted(
        (int(n.split(".")[0]), n) for n in names
        if n.endswith(".json") and n.split(".")[0].isdigit())
    for v, name in versions:
        if v <= start_version:
            continue
        with open(os.path.join(log_dir, name)) as f:
            for line in f:
                line = line.strip()
                if line:
                    state.apply(json.loads(line))
    if state.schema_raw is None:
        raise ValueError(f"delta log has no metaData action: {table_path}")
    return state


class DeltaScanOperator(ScanOperator):
    def __init__(self, table_path: str):
        self.table_path = table_path
        self.state = _replay_log(table_path)
        fields = [Field(f["name"], _delta_type_to_dtype(f["type"]))
                  for f in self.state.schema_raw["fields"]]
        self._schema = Schema(fields)

    def name(self) -> str:
        return f"DeltaScan({os.path.basename(os.path.normpath(self.table_path))})"

    def schema(self) -> Schema:
        return self._schema

    def can_absorb_select(self) -> bool:
        return True

    def can_absorb_filter(self) -> bool:
        return True

    def can_absorb_limit(self) -> bool:
        return True

    def approx_num_rows(self, pushdowns: Pushdowns) -> Optional[float]:
        total = 0
        for add in self.state.files.values():
            stats = add.get("stats")
            if not stats:
                return None
            total += json.loads(stats).get("numRecords", 0)
        if pushdowns.limit is not None:
            total = min(total, pushdowns.limit)
        return float(total)

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        from .parquet import _expr_to_arrow_filter, _zone_map_conjuncts

        schema = self._schema
        columns = pushdowns.columns
        out_schema = Schema([schema[c] for c in columns]) if columns is not None else schema
        conjuncts = _zone_map_conjuncts(pushdowns.filters) \
            if pushdowns.filters is not None else []
        part_cols = self.state.partition_columns
        # the arrow filter may reference partition columns absent from the
        # files; only push it into the parquet read when it doesn't
        refs = set()
        if pushdowns.filters is not None:
            from ..expressions import ColumnRef

            refs = {n._name for n in pushdowns.filters.walk()
                    if isinstance(n, ColumnRef)}
        arrow_filter = None
        if pushdowns.filters is not None and not (refs & set(part_cols)):
            arrow_filter = _expr_to_arrow_filter(pushdowns.filters)

        tasks: List[ScanTask] = []
        for path, add in sorted(self.state.files.items()):
            pvals = {c: _parse_partition_value(add.get("partitionValues", {}).get(c),
                                               schema[c].dtype)
                     for c in part_cols if c in schema.column_names()}
            if pvals and conjuncts and _pruned(pvals, conjuncts):
                continue
            if conjuncts and self._stats_prune(add, conjuncts):
                continue
            file_path = os.path.join(self.table_path, path)
            file_cols = None
            if columns is not None:
                file_cols = [c for c in columns if c not in part_cols]
            tasks.append(self._task(file_path, file_cols, arrow_filter, out_schema,
                                    pvals, add))
        return tasks

    def _stats_prune(self, add: dict, conjuncts: List[tuple]) -> bool:
        """Prune on the add action's min/max stats (delta writes them as JSON)."""
        stats = add.get("stats")
        if not stats:
            return False
        s = json.loads(stats)
        mins, maxs = s.get("minValues", {}), s.get("maxValues", {})
        for colname, op, val in conjuncts:
            lo, hi = mins.get(colname), maxs.get(colname)
            try:
                if op == "eq" and ((lo is not None and val < lo)
                                   or (hi is not None and val > hi)):
                    return True
                if op in ("lt", "le") and lo is not None and not (
                        lo < val if op == "lt" else lo <= val):
                    return True
                if op in ("gt", "ge") and hi is not None and not (
                        hi > val if op == "gt" else hi >= val):
                    return True
            except TypeError:
                continue
        return False

    def _task(self, file_path: str, file_cols, arrow_filter, out_schema: Schema,
              pvals: Dict[str, Any], add: dict) -> ScanTask:
        stats = add.get("stats")
        num_rows = json.loads(stats).get("numRecords") if stats else None

        def read():
            import pyarrow.parquet as pq

            from ..core.micropartition import MicroPartition
            from ..core.recordbatch import RecordBatch
            from ..core.series import Series

            table = pq.read_table(file_path, columns=file_cols, filters=arrow_filter)
            batch = RecordBatch.from_arrow(table)
            n = batch.num_rows
            cols = {s.name: s for s in batch.columns}
            out_cols = []
            for f in out_schema.fields:
                if f.name in cols:
                    out_cols.append(cols[f.name])
                else:  # partition column: constant from the add action
                    out_cols.append(Series.from_pylist([pvals.get(f.name)] * n,
                                                       f.name, dtype=f.dtype))
            out = RecordBatch(out_schema, out_cols, n).cast_to_schema(out_schema)
            yield MicroPartition(out_schema, [out])

        return ScanTask(read=read, schema=out_schema,
                        size_bytes=add.get("size"), num_rows=num_rows,
                        filters_applied=arrow_filter is not None,
                        limit_applied=False, source_label=file_path)


def _pruned(pvals: Dict[str, Any], conjuncts: List[tuple]) -> bool:
    for colname, op, val in conjuncts:
        if colname not in pvals or pvals[colname] is None:
            continue
        pv = pvals[colname]
        try:
            if op == "eq" and not (pv == val):
                return True
            if op == "lt" and not (pv < val):
                return True
            if op == "le" and not (pv <= val):
                return True
            if op == "gt" and not (pv > val):
                return True
            if op == "ge" and not (pv >= val):
                return True
        except TypeError:
            continue
    return False


# ======================================================================================
# Write path
# ======================================================================================


def _dtype_to_delta_type(dt: DataType) -> Any:
    """Inverse of _delta_type_to_dtype for schemaString emission."""
    if dt.is_struct():
        return {"type": "struct",
                "fields": [{"name": n, "type": _dtype_to_delta_type(t),
                            "nullable": True, "metadata": {}}
                           for n, t in dt.struct_fields]}
    if dt.is_list():
        return {"type": "array", "elementType": _dtype_to_delta_type(dt.inner),
                "containsNull": True}
    if dt.is_decimal():
        prec, sc = dt.params
        return f"decimal({prec},{sc})"
    simple = {
        DataType.string(): "string", DataType.int64(): "long",
        DataType.int32(): "integer", DataType.int16(): "short",
        DataType.int8(): "byte", DataType.float32(): "float",
        DataType.float64(): "double", DataType.bool(): "boolean",
        DataType.binary(): "binary", DataType.date(): "date",
    }
    if dt in simple:
        return simple[dt]
    if dt.is_temporal():
        return "timestamp"
    raise NotImplementedError(f"cannot map {dt} to a delta type")


def write_deltalake(df, table_path: str, mode: str = "append",
                    partition_cols: Optional[List[str]] = None):
    """Write a DataFrame as a Delta Lake table (reference:
    DataFrame.write_deltalake via the deltalake package; here the protocol is
    emitted directly — parquet data files + JSON transaction-log commit that
    read_deltalake() and any standard Delta reader replays).

    mode: "append" | "overwrite" | "error" | "ignore".
    Returns a DataFrame of the written file paths and row counts.
    """
    import time as _time
    import uuid as _uuid

    import pyarrow.parquet as pq

    from .. import api as _api

    log_dir = os.path.join(table_path, "_delta_log")
    exists = os.path.isdir(log_dir)
    if exists:
        if mode == "error":
            raise FileExistsError(f"delta table already exists: {table_path}")
        if mode == "ignore":
            return _api.from_pydict({"path": [], "rows": []})
    os.makedirs(log_dir, exist_ok=True)

    parts = list(partition_cols or [])
    schema = df.schema
    for p in parts:
        if p not in schema.column_names():
            raise ValueError(f"partition column {p!r} not in schema")

    versions = [int(n.split(".")[0]) for n in os.listdir(log_dir)
                if n.endswith(".json") and n.split(".")[0].isdigit()]
    version = (max(versions) + 1) if versions else 0

    actions: List[dict] = []
    now_ms = int(_time.time() * 1000)
    if version == 0:
        schema_string = json.dumps({
            "type": "struct",
            "fields": [{"name": f.name, "type": _dtype_to_delta_type(f.dtype),
                        "nullable": True, "metadata": {}} for f in schema],
        })
        actions.append({"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}})
        actions.append({"metaData": {
            "id": str(_uuid.uuid4()), "format": {"provider": "parquet", "options": {}},
            "schemaString": schema_string, "partitionColumns": parts,
            "configuration": {}, "createdTime": now_ms,
        }})
    if mode == "overwrite" and exists:
        state = _replay_log(table_path)
        for path in state.files:
            actions.append({"remove": {"path": path, "deletionTimestamp": now_ms,
                                       "dataChange": True}})

    import pyarrow as pa

    table = df.to_arrow()
    written_paths: List[str] = []
    written_rows: List[int] = []

    def _fmt_pv(v: Any) -> Optional[str]:
        if v is None:
            return None
        return str(v)

    def _write_one(tbl, pvals: Dict[str, str], subdir: str) -> None:
        data_tbl = tbl.drop_columns(parts) if parts else tbl
        fname = f"part-{version:05d}-{_uuid.uuid4().hex}.parquet"
        rel = os.path.join(subdir, fname) if subdir else fname
        abs_path = os.path.join(table_path, rel)
        os.makedirs(os.path.dirname(abs_path), exist_ok=True)
        pq.write_table(data_tbl, abs_path)
        actions.append({"add": {
            "path": rel.replace(os.sep, "/"), "partitionValues": pvals,
            "size": os.path.getsize(abs_path), "modificationTime": now_ms,
            "dataChange": True,
        }})
        written_paths.append(rel)
        written_rows.append(data_tbl.num_rows)

    if not parts:
        _write_one(table, {}, "")
    else:
        import pyarrow.compute as _pc

        keys = [table.column(p) for p in parts]
        combo = table.group_by(parts).aggregate([]).to_pylist()
        for row in combo:
            mask = None
            for p in parts:
                m = _pc.equal(table.column(p), pa.scalar(row[p])) if row[p] is not None \
                    else _pc.is_null(table.column(p))
                mask = m if mask is None else _pc.and_(mask, m)
            sub = table.filter(mask)
            pvals = {p: _fmt_pv(row[p]) for p in parts}
            subdir = "/".join(f"{p}={pvals[p] if pvals[p] is not None else '__HIVE_DEFAULT_PARTITION__'}"
                              for p in parts)
            _write_one(sub, pvals, subdir)

    with open(os.path.join(log_dir, f"{version:020d}.json"), "w") as f:
        for a in actions:
            f.write(json.dumps(a) + "\n")

    return _api.from_pydict({"path": written_paths, "rows": written_rows})
