"""CSV scan operator (reference parity: src/daft-csv — streaming reader with schema
inference, delimiter/header options; local-filesystem subset, pyarrow-backed)."""

from __future__ import annotations

import os
from typing import List, Optional, Union

import pyarrow as pa
import pyarrow.csv as pacsv

from ..core.micropartition import MicroPartition
from ..schema import Schema
from .paths import expand_paths
from .scan import Pushdowns, ScanOperator, ScanTask


class CsvScanOperator(ScanOperator):
    def __init__(self, path: Union[str, List[str]], schema: Optional[Schema] = None,
                 has_headers: bool = True, delimiter: str = ",", **_options):
        self._paths = expand_paths(path, (".csv", ".tsv"))
        if not self._paths:
            raise FileNotFoundError(f"no csv files matched {path!r}")
        self._schema = schema
        self._has_headers = has_headers
        self._delimiter = delimiter

    def name(self) -> str:
        return f"CsvScan({len(self._paths)} files)"

    def _read_opts(self):
        ropts = pacsv.ReadOptions(autogenerate_column_names=not self._has_headers)
        popts = pacsv.ParseOptions(delimiter=self._delimiter)
        return ropts, popts

    def schema(self) -> Schema:
        if self._schema is None:
            ropts, popts = self._read_opts()
            # infer from the first block of the first file
            ropts_head = pacsv.ReadOptions(
                autogenerate_column_names=not self._has_headers, block_size=1 << 20
            )
            from .object_store import open_input
            with pacsv.open_csv(open_input(self._paths[0]), read_options=ropts_head, parse_options=popts) as r:
                batch = r.read_next_batch()
            if not self._has_headers:
                # rename f0.. to column_1.. (reference naming)
                t = pa.Table.from_batches([batch])
                t = t.rename_columns([f"column_{i+1}" for i in range(t.num_columns)])
                batch = t.to_batches()[0] if t.num_rows else t.schema.empty_table().to_batches()
                self._schema = Schema.from_arrow(t.schema)
            else:
                self._schema = Schema.from_arrow(batch.schema)
        return self._schema

    def can_absorb_select(self) -> bool:
        return True

    def can_absorb_limit(self) -> bool:
        return True

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        schema = self.schema()
        columns = pushdowns.columns
        out_schema = Schema([schema[c] for c in columns]) if columns is not None else schema
        tasks = []
        for path in self._paths:
            tasks.append(ScanTask(
                read=self._make_reader(path, columns, pushdowns.limit, out_schema),
                schema=out_schema,
                size_bytes=os.path.getsize(path) if os.path.exists(path) else None,
                source_label=path,
            ))
        return tasks

    def _make_reader(self, path: str, columns, limit, out_schema: Schema):
        ropts, popts = self._read_opts()

        def read():
            produced = 0
            from .object_store import open_input
            with pacsv.open_csv(open_input(path), read_options=ropts, parse_options=popts) as reader:
                for batch in reader:
                    t = pa.Table.from_batches([batch])
                    if not self._has_headers:
                        t = t.rename_columns([f"column_{i+1}" for i in range(t.num_columns)])
                    if columns is not None:
                        t = t.select(columns)
                    if limit is not None:
                        if produced >= limit:
                            return
                        if produced + t.num_rows > limit:
                            t = t.slice(0, limit - produced)
                    produced += t.num_rows
                    yield MicroPartition.from_arrow(t).cast_to_schema(out_schema)

        return read
