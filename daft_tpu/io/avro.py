"""Minimal Avro Object Container File reader/writer.

Iceberg's manifest lists and manifest files are Avro (spec:
https://avro.apache.org/docs/current/specification/ — binary encoding +
object container framing). The image ships no avro library, so this
implements the subset Iceberg metadata needs: records, unions, arrays, maps,
enums, fixed, all primitives, and the null/deflate codecs. The writer exists
for round-trip tests and for producing spec-shaped fixtures.

Reference parity: the reference reads these through the iceberg-rust /
pyiceberg dependency (daft/io/iceberg/iceberg_scan.py); here the format is
implemented directly.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

MAGIC = b"Obj\x01"


# ======================================================================================
# binary encoding
# ======================================================================================


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.data[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("truncated avro data")
        self.pos += n
        return b

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def at_end(self) -> bool:
        return self.pos >= len(self.data)


def _encode_long(out: io.BytesIO, v: int) -> None:
    u = (v << 1) if v >= 0 else ((-v) << 1) - 1  # zigzag
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def decode(schema: Any, r: _Reader) -> Any:
    """Decode one value of `schema` (parsed Avro schema JSON) from r."""
    if isinstance(schema, str):
        t = schema
        if t == "null":
            return None
        if t == "boolean":
            return r.read(1) != b"\x00"
        if t in ("int", "long"):
            return r.read_long()
        if t == "float":
            return struct.unpack("<f", r.read(4))[0]
        if t == "double":
            return struct.unpack("<d", r.read(8))[0]
        if t == "bytes":
            return r.read_bytes()
        if t == "string":
            return r.read_bytes().decode("utf-8")
        raise NotImplementedError(f"avro type {t!r}")
    if isinstance(schema, list):  # union
        idx = r.read_long()
        return decode(schema[idx], r)
    t = schema["type"]
    if t == "record":
        return {f["name"]: decode(f["type"], r) for f in schema["fields"]}
    if t == "array":
        out = []
        while True:
            n = r.read_long()
            if n == 0:
                return out
            if n < 0:
                r.read_long()  # block byte size (skippable); we decode anyway
                n = -n
            for _ in range(n):
                out.append(decode(schema["items"], r))
    if t == "map":
        out = {}
        while True:
            n = r.read_long()
            if n == 0:
                return out
            if n < 0:
                r.read_long()
                n = -n
            for _ in range(n):
                k = r.read_bytes().decode("utf-8")
                out[k] = decode(schema["values"], r)
    if t == "fixed":
        return r.read(schema["size"])
    if t == "enum":
        return schema["symbols"][r.read_long()]
    # named/logical types wrap a primitive
    if t in ("int", "long", "float", "double", "bytes", "string", "boolean", "null"):
        return decode(t, r)
    raise NotImplementedError(f"avro type {t!r}")


def encode(schema: Any, v: Any, out: io.BytesIO) -> None:
    if isinstance(schema, str):
        t = schema
        if t == "null":
            return
        if t == "boolean":
            out.write(b"\x01" if v else b"\x00")
            return
        if t in ("int", "long"):
            _encode_long(out, int(v))
            return
        if t == "float":
            out.write(struct.pack("<f", v))
            return
        if t == "double":
            out.write(struct.pack("<d", v))
            return
        if t == "bytes":
            _encode_long(out, len(v))
            out.write(v)
            return
        if t == "string":
            b = v.encode("utf-8")
            _encode_long(out, len(b))
            out.write(b)
            return
        raise NotImplementedError(f"avro type {t!r}")
    if isinstance(schema, list):  # union: pick the first branch matching None-ness
        if v is None:
            idx = schema.index("null")
        else:
            idx = next(i for i, s in enumerate(schema) if s != "null")
        _encode_long(out, idx)
        encode(schema[idx], v, out)
        return
    t = schema["type"]
    if t == "record":
        for f in schema["fields"]:
            encode(f["type"], v[f["name"]], out)
        return
    if t == "array":
        if v:
            _encode_long(out, len(v))
            for item in v:
                encode(schema["items"], item, out)
        _encode_long(out, 0)
        return
    if t == "map":
        if v:
            _encode_long(out, len(v))
            for k, val in v.items():
                encode("string", k, out)
                encode(schema["values"], val, out)
        _encode_long(out, 0)
        return
    if t == "fixed":
        out.write(v)
        return
    if t == "enum":
        _encode_long(out, schema["symbols"].index(v))
        return
    if t in ("int", "long", "float", "double", "bytes", "string", "boolean", "null"):
        encode(t, v, out)
        return
    raise NotImplementedError(f"avro type {t!r}")


# ======================================================================================
# object container files
# ======================================================================================

_META_SCHEMA = {"type": "map", "values": "bytes"}


def read_container(data: bytes) -> Tuple[Any, List[dict]]:
    """Parse an Avro object container file -> (schema, records)."""
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ValueError("not an avro object container file")
    meta = decode(_META_SCHEMA, r)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = r.read(16)
    records: List[dict] = []
    while not r.at_end():
        count = r.read_long()
        size = r.read_long()
        block = r.read(size)
        if codec == "deflate":
            block = zlib.decompressobj(-15).decompress(block)
        elif codec != "null":
            raise NotImplementedError(f"avro codec {codec!r}")
        br = _Reader(block)
        for _ in range(count):
            records.append(decode(schema, br))
        if r.read(16) != sync:
            raise ValueError("avro sync marker mismatch")
    return schema, records


def write_container(path: str, schema: Any, records: List[dict],
                    codec: str = "deflate") -> None:
    body = io.BytesIO()
    for rec in records:
        encode(schema, rec, body)
    block = body.getvalue()
    if codec == "deflate":
        comp = zlib.compressobj(9, zlib.DEFLATED, -15)
        block = comp.compress(block) + comp.flush()
    elif codec != "null":
        raise NotImplementedError(f"avro codec {codec!r}")
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
            "avro.codec": codec.encode("utf-8")}
    encode(_META_SCHEMA, meta, out)
    sync = os.urandom(16)
    out.write(sync)
    _encode_long(out, len(records))
    _encode_long(out, len(block))
    out.write(block)
    out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())
