"""Torch dataset adapters (reference parity: daft/dataframe/to_torch.py)."""

from __future__ import annotations


class DataFrameMapDataset:
    """torch.utils.data.Dataset view of a materialized DataFrame."""

    def __init__(self, df):
        import torch.utils.data  # noqa: F401  — fail early if torch missing

        self._rows = df.to_pylist()

    def __len__(self) -> int:
        return len(self._rows)

    def __getitem__(self, i: int) -> dict:
        return self._rows[i]


class DataFrameIterDataset:
    """torch.utils.data.IterableDataset view streaming partitions."""

    def __init__(self, df):
        import torch.utils.data

        self._df = df

        class _Iter(torch.utils.data.IterableDataset):
            def __iter__(_self):
                return self._df.iter_rows()

        self._inner = _Iter()

    def __iter__(self):
        return iter(self._inner)
