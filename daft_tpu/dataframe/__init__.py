from .dataframe import DataFrame, GroupedDataFrame

__all__ = ["DataFrame", "GroupedDataFrame"]
