"""DataFrame: the lazy user-facing API.

Reference parity: daft/dataframe/dataframe.py:115 (~150 methods). Every method
appends to a LogicalPlanBuilder; collect() optimizes, translates and executes,
caching result partitions so downstream queries reuse them (reference's
PartitionSetCache behavior).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

from ..core.micropartition import MicroPartition
from ..expressions import AggExpr, Expression, col, lit
from ..plan.builder import ColumnInput, LogicalPlanBuilder, _to_expr, _to_exprs
from ..schema import Schema


class DataFrame:
    def __init__(self, builder: LogicalPlanBuilder):
        self._builder = builder
        self._result: Optional[List[MicroPartition]] = None

    # ---- metadata ----------------------------------------------------------------
    @property
    def schema(self) -> Schema:
        return self._builder.schema()

    @property
    def column_names(self) -> List[str]:
        return self.schema.column_names()

    def __repr__(self) -> str:
        if self._result is not None:
            return self._preview_string()
        return f"DataFrame(schema={self.schema}, not materialized)"

    @property
    def columns(self) -> List[Expression]:
        """Columns as a list of Expressions (reference: DataFrame.columns)."""
        return [col(f.name) for f in self.schema]

    def metrics(self):
        """Per-operator execution metrics of the materialized plan as a
        RecordBatch (reference: DataFrame.metrics). Runs the plan under a
        stats collector if it has not been materialized with one."""
        from ..core.recordbatch import RecordBatch
        from ..observability.runtime_stats import (StatsCollector,
                                                   current_collector,
                                                   set_collector)
        from ..runners import get_or_create_runner

        collector = StatsCollector()
        prev = current_collector()
        set_collector(collector)
        try:
            for _ in get_or_create_runner().run_iter(self._builder):
                pass
        finally:
            set_collector(prev)
        rows: Dict[str, list] = {"operator": [], "rows_out": [], "batches": [],
                                 "self_time_s": []}
        for s in collector.finish():
            rows["operator"].append(s.name)
            rows["rows_out"].append(s.rows_out)
            rows["batches"].append(s.batches_out)
            rows["self_time_s"].append(s.seconds)
        return RecordBatch.from_pydict(rows)

    def explain(self, show_all: bool = False) -> str:
        s = "== Unoptimized Logical Plan ==\n" + self._builder.plan.display()
        if show_all:
            opt = self._builder.optimize()
            s += "\n\n== Optimized Logical Plan ==\n" + opt.plan.display()
            from ..plan.physical import translate

            s += "\n\n== Physical Plan ==\n" + translate(opt.plan).display()
        return s

    def explain_analyze(self, profile: Optional[str] = None) -> str:
        """Execute the plan through the configured runner collecting
        per-operator runtime stats; returns the plans plus an operator table
        (rows out / batches / self time split into compute / starve /
        blocked) — reference: EXPLAIN ANALYZE over runtime_stats. On a
        distributed runner the report additionally renders the stage DAG
        rollup (per-stage task counts, min/median/max task time skew, queue
        wait, shuffle volumes, straggler flags, per-worker attribution) from
        the run's QueryTrace, plus the per-query metrics-registry deltas
        (device batches, shuffle bytes) so engine-path attribution is in the
        report, not only in bench.py.

        `profile="trace.json"` additionally writes the query's timeline as
        Chrome trace-event JSON (QueryTrace.to_chrome_trace) — open it in
        Perfetto (ui.perfetto.dev) or chrome://tracing. Works on both the
        native runner (driver lanes only) and the distributed runner (plus
        per-worker task lanes and device/io spans)."""
        import json
        import time

        from ..observability.metrics import registry
        from ..observability.runtime_stats import (SpanRecorder, StatsCollector,
                                                   current_collector,
                                                   current_spans, format_stats,
                                                   set_collector, set_spans)
        from ..plan.physical import translate
        from ..runners import get_or_create_runner

        optimized = self._builder.optimize()
        phys = translate(optimized.plan)
        collector = StatsCollector()
        prev = current_collector()
        runner = get_or_create_runner()
        reg_before = registry().snapshot()
        set_collector(collector)
        span_rec = prev_spans = None
        if profile:
            # capture real wall-clock device/io spans for the timeline
            span_rec = SpanRecorder()
            prev_spans = current_spans()
            set_spans(span_rec)
        t_wall0 = time.time()
        t0 = time.perf_counter()
        try:
            for _ in runner.run_iter(self._builder):
                pass
        finally:
            set_collector(prev)
            if profile:
                set_spans(prev_spans)
        total = time.perf_counter() - t0
        stats = collector.finish()
        report = ("== Physical Plan ==\n" + phys.display()
                  + "\n\n== Runtime Stats ==\n"
                  + format_stats(stats, total))
        trace = getattr(runner, "last_trace", None)
        if trace is not None and trace.tasks:
            report += "\n\n== Distributed Stages ==\n" + trace.render()
        deltas = registry().diff(reg_before)
        if deltas:
            report += "\n\n== Engine Counters ==\n" + "\n".join(
                f"{k:<32} {v:>12g}" for k, v in sorted(deltas.items()))
        if profile:
            if trace is None:
                # native runner: synthesize an empty trace for driver lanes
                from ..distributed.trace import QueryTrace

                trace = QueryTrace("")
                trace.started_wall = t_wall0
            data = trace.to_chrome_trace(driver_ops=stats,
                                         driver_spans=span_rec.drain(),
                                         total_seconds=total)
            with open(profile, "w") as f:
                json.dump(data, f)
        return report

    def explain_placement(self) -> str:
        """Execute the plan and report every device-placement decision the
        cost model made: chosen tier, per-term cost tables for every priced
        tier (rtt / h2d / compute / d2h / ici / factorize, residency
        credit), the what-if margin (how close the losing tier was), cache-
        hit vs fresh verdicts, and — for dispatched device stages — the
        observed seconds and per-row model error next to the prediction.
        The raw records also ride QueryEnd.placements (event log schema v9)
        and the process ledger behind the dashboard's /api/placement."""
        from ..observability import placement
        from ..runners import get_or_create_runner

        with placement.query_scope() as scope:
            for _ in get_or_create_runner().run_iter(self._builder):
                pass
        return placement.render(scope.records())

    def _next(self, builder: LogicalPlanBuilder) -> "DataFrame":
        return DataFrame(builder)

    # ---- transforms --------------------------------------------------------------
    def select(self, *columns: ColumnInput) -> "DataFrame":
        exprs = _to_exprs(columns)
        # expand unnest() markers into one column per struct field
        from ..expressions.expressions import Unnest

        if any(isinstance(e, Unnest) for e in exprs):
            schema = self.schema
            expanded = []
            for e in exprs:
                if isinstance(e, Unnest):
                    dt = e.child.to_field(schema).dtype
                    if not dt.is_struct():
                        raise ValueError(f"unnest() requires a struct column, got {dt}")
                    for fname, _ft in dt.struct_fields:
                        expanded.append(e.child.struct.get(fname).alias(fname))
                else:
                    expanded.append(e)
            exprs = expanded
        return self._next(self._builder.select(exprs))

    def with_column(self, name: str, expr: ColumnInput) -> "DataFrame":
        return self.with_columns({name: expr})

    def with_columns(self, columns: Dict[str, ColumnInput]) -> "DataFrame":
        exprs = [_to_expr(e).alias(n) for n, e in columns.items()]
        return self._next(self._builder.with_columns(exprs))

    def with_column_renamed(self, existing: str, new: str) -> "DataFrame":
        return self._next(self._builder.rename({existing: new}))

    def with_columns_renamed(self, mapping: Dict[str, str]) -> "DataFrame":
        return self._next(self._builder.rename(mapping))

    def exclude(self, *names: str) -> "DataFrame":
        return self._next(self._builder.exclude(list(names)))

    def where(self, predicate: ColumnInput) -> "DataFrame":
        if isinstance(predicate, str):
            from ..sql import sql_expr

            predicate = sql_expr(predicate)
        return self._next(self._builder.filter(_to_expr(predicate)))

    filter = where

    def limit(self, n: int) -> "DataFrame":
        return self._next(self._builder.limit(n))

    def offset(self, n: int) -> "DataFrame":
        return self._next(self._builder.offset(n))

    def sample(self, fraction: float, with_replacement: bool = False,
               seed: Optional[int] = None) -> "DataFrame":
        return self._next(self._builder.sample(fraction, with_replacement, seed))

    def explode(self, *columns: ColumnInput) -> "DataFrame":
        return self._next(self._builder.explode(_to_exprs(columns)))

    def unpivot(self, ids: Sequence[ColumnInput], values: Sequence[ColumnInput] = (),
                variable_name: str = "variable", value_name: str = "value") -> "DataFrame":
        ids_ex = _to_exprs(ids if isinstance(ids, (list, tuple)) else [ids])
        if not values:
            id_names = {e.name() for e in ids_ex}
            values = [c for c in self.column_names if c not in id_names]
        vals_ex = _to_exprs(values if isinstance(values, (list, tuple)) else [values])
        return self._next(self._builder.unpivot(ids_ex, vals_ex, variable_name, value_name))

    melt = unpivot

    def distinct(self, *on: ColumnInput) -> "DataFrame":
        return self._next(self._builder.distinct(_to_exprs(on) if on else None))

    unique = distinct
    drop_duplicates = distinct

    def sort(self, by: Union[ColumnInput, List[ColumnInput]],
             desc: Union[bool, List[bool]] = False,
             nulls_first: Optional[Union[bool, List[bool]]] = None) -> "DataFrame":
        by_list = by if isinstance(by, list) else [by]
        return self._next(self._builder.sort(by_list, desc, nulls_first))

    def add_monotonically_increasing_id(self, column_name: str = "id") -> "DataFrame":
        return self._next(self._builder.add_monotonically_increasing_id(column_name))

    _add_monotonically_increasing_id = add_monotonically_increasing_id

    def repartition(self, num: Optional[int], *partition_by: ColumnInput) -> "DataFrame":
        if partition_by:
            return self._next(self._builder.repartition(num, "hash", _to_exprs(partition_by)))
        return self._next(self._builder.repartition(num, "random"))

    def into_partitions(self, num: int) -> "DataFrame":
        return self._next(self._builder.into_partitions(num))

    def into_batches(self, batch_size: int) -> "DataFrame":
        return self._next(self._builder.into_batches(batch_size))

    # ---- joins -------------------------------------------------------------------
    def join(self, other: "DataFrame",
             on: Optional[Union[ColumnInput, List[ColumnInput]]] = None,
             left_on: Optional[Union[ColumnInput, List[ColumnInput]]] = None,
             right_on: Optional[Union[ColumnInput, List[ColumnInput]]] = None,
             how: str = "inner", prefix: Optional[str] = None, suffix: Optional[str] = None,
             strategy: Optional[str] = None, null_equals_null: bool = False) -> "DataFrame":
        if on is not None:
            left_on = right_on = on
        if how == "cross":
            return self._next(self._builder.cross_join(other._builder, prefix, suffix))
        if left_on is None or right_on is None:
            raise ValueError("join requires `on` or both `left_on` and `right_on`")
        lo = left_on if isinstance(left_on, list) else [left_on]
        ro = right_on if isinstance(right_on, list) else [right_on]
        return self._next(self._builder.join(other._builder, lo, ro, how, prefix, suffix,
                                             strategy, null_equals_null))

    def concat(self, other: "DataFrame") -> "DataFrame":
        return self._next(self._builder.concat(other._builder))

    union_all = concat

    def union(self, other: "DataFrame") -> "DataFrame":
        return self.concat(other).distinct()

    def intersect(self, other: "DataFrame") -> "DataFrame":
        # semi join on all columns + distinct (reference: ops/intersect.rs
        # semantics); SQL set ops treat NULL keys as equal
        names = self.column_names
        return self.join(other, left_on=[col(n) for n in names],
                         right_on=[col(n) for n in names], how="semi",
                         null_equals_null=True).distinct()

    def union_by_name(self, other: "DataFrame") -> "DataFrame":
        """Distinct union with columns matched by name (reference:
        DataFrame.union_by_name); columns absent on one side fill with nulls."""
        return self.union_all_by_name(other).distinct()

    def union_all_by_name(self, other: "DataFrame") -> "DataFrame":
        """Union keeping duplicates, columns matched by name; missing columns
        become nulls (reference: DataFrame.union_all_by_name)."""
        from ..expressions import lit

        all_names = list(self.column_names)
        for n in other.column_names:
            if n not in all_names:
                all_names.append(n)

        def conform(df: "DataFrame") -> "DataFrame":
            have = set(df.column_names)
            exprs = []
            for n in all_names:
                if n in have:
                    exprs.append(col(n))
                else:
                    dtype = (other if df is self else self).schema[n].dtype
                    exprs.append(lit(None).cast(dtype).alias(n))
            return df.select(*exprs)

        return conform(self).concat(conform(other))

    def intersect_all(self, other: "DataFrame") -> "DataFrame":
        """INTERSECT ALL: multiset intersection — each row kept min(l, r)
        times. Row-numbering each duplicate within its key group turns the
        multiset op into a plain semi join on (columns..., occurrence#)."""
        return self._multiset_setop(other, "semi")

    def except_all(self, other: "DataFrame") -> "DataFrame":
        """EXCEPT ALL: multiset difference — each row kept max(l - r, 0) times."""
        return self._multiset_setop(other, "anti")

    def _multiset_setop(self, other: "DataFrame", how: str) -> "DataFrame":
        from ..functions import row_number
        from ..window import Window

        names = list(self.column_names)
        w = Window().partition_by(*names).order_by(names[0])
        rn = "__occurrence__"
        left = self.with_column(rn, row_number().over(w))
        right = other.with_column(rn, row_number().over(w))
        keys = [col(n) for n in names] + [col(rn)]
        return left.join(right, left_on=keys, right_on=keys, how=how,
                         null_equals_null=True).select(*[col(n) for n in names])

    def shuffle(self, seed: Optional[int] = None) -> "DataFrame":
        """Randomly reorder rows (reference: DataFrame.shuffle — a global sort
        on a random key)."""
        import random as _random

        from ..expressions import lit

        rng_seed = seed if seed is not None else _random.randrange(2 ** 31)
        tmp = "__shuffle_key__"
        keyed = self.with_column(tmp, (col(self.column_names[0]).hash(seed=rng_seed)
                                       if self.column_names else lit(0)))
        return keyed.sort(tmp).exclude(tmp)

    def except_distinct(self, other: "DataFrame") -> "DataFrame":
        """EXCEPT DISTINCT: rows of self absent from other (NULLs match NULLs,
        per SQL set-op semantics)."""
        names = self.column_names
        return self.join(other, left_on=[col(n) for n in names],
                         right_on=[col(n) for n in names], how="anti",
                         null_equals_null=True).distinct()

    except_ = except_distinct

    # ---- aggregation -------------------------------------------------------------
    def groupby(self, *group_by: ColumnInput) -> "GroupedDataFrame":
        return GroupedDataFrame(self, _to_exprs(group_by))

    group_by = groupby

    def agg(self, *aggs: Expression) -> "DataFrame":
        return self._next(self._builder.aggregate(_flatten_aggs(aggs), []))

    def sum(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[_to_expr(c).sum() for c in cols])

    def mean(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[_to_expr(c).mean() for c in cols])

    def min(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[_to_expr(c).min() for c in cols])

    def max(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[_to_expr(c).max() for c in cols])

    def count(self, *cols: ColumnInput) -> "DataFrame":
        if not cols:
            return self.agg(lit(1).count("all").alias("count"))
        return self.agg(*[_to_expr(c).count() for c in cols])

    def count_rows(self) -> int:
        return self.count().to_pydict()["count"][0]

    def stddev(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[_to_expr(c).stddev() for c in cols])

    def var(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[_to_expr(c).var() for c in cols])

    def skew(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[_to_expr(c).skew() for c in cols])

    def any_value(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[_to_expr(c).any_value() for c in cols])

    def agg_list(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[AggExpr("list", _to_expr(c)) for c in cols])

    list_agg = agg_list

    def agg_set(self, *cols: ColumnInput) -> "DataFrame":
        """Distinct values per column as lists (reference: DataFrame.agg_set)."""
        return self.agg(*[AggExpr("set", _to_expr(c)) for c in cols])

    list_agg_distinct = agg_set

    def agg_concat(self, *cols: ColumnInput) -> "DataFrame":
        return self.agg(*[AggExpr("concat", _to_expr(c)) for c in cols])

    def string_agg(self, *cols: ColumnInput, delimiter: str = "") -> "DataFrame":
        """Concatenate string values into one string per column (reference:
        DataFrame.string_agg); implemented as list-agg + list.join."""
        names = [_to_expr(c).name() for c in cols]
        out = self.agg(*[AggExpr("list", _to_expr(c)).alias(n)
                         for c, n in zip(cols, names)])
        return out.select(*[col(n).list.join(delimiter).alias(n) for n in names])

    def __len__(self) -> int:
        return self.count_rows()

    def pivot(self, group_by: Union[ColumnInput, List[ColumnInput]], pivot_col: ColumnInput,
              value_col: ColumnInput, agg_fn: str,
              names: Optional[List[str]] = None) -> "DataFrame":
        gb = group_by if isinstance(group_by, list) else [group_by]
        if names is None:
            pc_expr = _to_expr(pivot_col)
            vals = (self.select(pc_expr).distinct().sort(pc_expr.name()).to_pydict())[pc_expr.name()]
            names = [str(v) for v in vals if v is not None]
        return self._next(self._builder.pivot(gb, pivot_col, value_col, agg_fn, names))

    # ---- materialization ---------------------------------------------------------
    def _materialize(self) -> List[MicroPartition]:
        if self._result is None:
            from ..runners import get_or_create_runner

            self._result = get_or_create_runner().run(self._builder)
        return self._result

    def collect(self) -> "DataFrame":
        parts = self._materialize()
        # pin results into the plan so downstream ops read from memory
        new = DataFrame(LogicalPlanBuilder.from_in_memory(self.schema, parts))
        new._result = parts
        return new

    def iter_partitions(self) -> Iterator[MicroPartition]:
        if self._result is not None:
            yield from self._result
            return
        from ..runners import get_or_create_runner

        yield from get_or_create_runner().run_iter(self._builder)

    def iter_rows(self) -> Iterator[dict]:
        for part in self.iter_partitions():
            for b in part.batches:
                yield from b.to_pylist()

    def __iter__(self):
        return self.iter_rows()

    def show(self, n: int = 8) -> None:
        print(self.limit(n)._preview_string(n))

    def _preview_string(self, n: int = 8) -> str:
        parts = self.limit(n)._materialize()
        mp = MicroPartition.concat(parts) if parts else MicroPartition.empty(self.schema)
        return _format_table(mp, self.schema)

    # ---- conversions -------------------------------------------------------------
    def to_pydict(self) -> Dict[str, list]:
        parts = self._materialize()
        mp = MicroPartition.concat(parts) if parts else MicroPartition.empty(self.schema)
        return mp.to_pydict()

    def to_pylist(self) -> List[dict]:
        return list(self.iter_rows())

    def to_arrow(self):
        parts = self._materialize()
        mp = MicroPartition.concat(parts) if parts else MicroPartition.empty(self.schema)
        return mp.to_arrow()

    def to_arrow_iter(self):
        for part in self.iter_partitions():
            for b in part.batches:
                yield from b.to_arrow().to_batches()

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def to_torch_map_dataset(self):
        from .to_torch import DataFrameMapDataset

        return DataFrameMapDataset(self)

    def to_torch_iter_dataset(self):
        from .to_torch import DataFrameIterDataset

        return DataFrameIterDataset(self)

    def to_jax(self, pad_to: Optional[int] = None) -> Dict[str, Any]:
        """Materialize device-compatible columns as jax Arrays (host→HBM transfer)."""
        parts = self._materialize()
        mp = MicroPartition.concat(parts) if parts else MicroPartition.empty(self.schema)
        batch = mp.concat_or_empty()
        out = {}
        for s in batch.columns:
            if s.dtype.is_device_compatible():
                out[s.name] = s.to_device(pad_to=pad_to)
        return out

    # ---- writes ------------------------------------------------------------------
    def write_parquet(self, root_dir: str, compression: str = "snappy",
                      partition_cols: Optional[List[ColumnInput]] = None,
                      write_mode: str = "append", checkpoint=None) -> "DataFrame":
        """checkpoint=(CheckpointStore, key_column) enables resume: rows whose
        key a prior run sealed are skipped (reference: daft-checkpoint)."""
        from ..io.writers import WriteInfo

        info = WriteInfo("parquet", root_dir, {"compression": compression},
                         _to_exprs(partition_cols) if partition_cols else None, write_mode,
                         checkpoint=checkpoint)
        return self._write(info)

    def write_csv(self, root_dir: str, partition_cols: Optional[List[ColumnInput]] = None,
                  write_mode: str = "append") -> "DataFrame":
        from ..io.writers import WriteInfo

        info = WriteInfo("csv", root_dir, {},
                         _to_exprs(partition_cols) if partition_cols else None, write_mode)
        return self._write(info)

    def write_json(self, root_dir: str, write_mode: str = "append") -> "DataFrame":
        from ..io.writers import WriteInfo

        info = WriteInfo("json", root_dir, {}, None, write_mode)
        return self._write(info)

    def pipe(self, fn, *args, **kwargs):
        """Apply fn(self, *args, **kwargs) — fluent composition helper."""
        return fn(self, *args, **kwargs)

    def transform(self, fn, *args, **kwargs) -> "DataFrame":
        out = fn(self, *args, **kwargs)
        if not isinstance(out, DataFrame):
            raise ValueError(f"transform fn must return a DataFrame, got {type(out).__name__}")
        return out

    def drop_null(self, *cols: ColumnInput) -> "DataFrame":
        """Drop rows with nulls in the given columns (all columns if none)."""
        exprs = _to_exprs(cols) if cols else [_to_expr(c) for c in self.column_names]
        pred = exprs[0].not_null()
        for e in exprs[1:]:
            pred = pred & e.not_null()
        return self.where(pred)

    def drop_nan(self, *cols: ColumnInput) -> "DataFrame":
        """Drop rows with NaNs in the given float columns (all float columns
        if none)."""
        if cols:
            exprs = _to_exprs(cols)
        else:
            exprs = [_to_expr(f.name) for f in self.schema if f.dtype.is_floating()]
        if not exprs:
            return self
        pred = None
        for e in exprs:
            c = e.is_null() | ~e.float.is_nan()
            pred = c if pred is None else pred & c
        return self.where(pred)

    def describe(self) -> "DataFrame":
        """Per-numeric-column summary: count / mean / stddev / min / max
        (reference: DataFrame.describe / summarize)."""
        from ..expressions import col as _col

        aggs = []
        for f in self.schema:
            if f.dtype.is_numeric() and not f.dtype.is_decimal():
                c = _col(f.name)
                aggs += [c.count().alias(f"{f.name}_count"),
                         c.mean().alias(f"{f.name}_mean"),
                         c.stddev().alias(f"{f.name}_stddev"),
                         c.min().alias(f"{f.name}_min"),
                         c.max().alias(f"{f.name}_max")]
        if not aggs:
            raise ValueError("describe() needs at least one numeric column")
        return self.agg(*aggs)

    summarize = describe

    def write_sink(self, sink) -> "DataFrame":
        """Write through a custom DataSink (reference: daft/io/sink.py —
        start() once, write() per partition, finalize() -> result table)."""
        from ..io.sink import _SinkWriteInfo

        return self._write(_SinkWriteInfo(sink))

    def write_deltalake(self, table_path: str, mode: str = "append",
                        partition_cols: Optional[List[str]] = None) -> "DataFrame":
        """Write as a Delta Lake table: parquet data files + a JSON
        transaction-log commit (reference: DataFrame.write_deltalake)."""
        from ..io.delta import write_deltalake

        return write_deltalake(self, table_path, mode, partition_cols)

    def write_iceberg(self, table_path: str, mode: str = "append",
                      partition_cols: Optional[List[str]] = None) -> "DataFrame":
        """Write as an Iceberg v2 table: parquet data files + Avro manifests +
        metadata JSON (reference: DataFrame.write_iceberg via pyiceberg)."""
        from ..io.iceberg import write_iceberg

        return write_iceberg(self, table_path, mode, partition_cols)

    def write_sql(self, table_name: str, connection,
                  mode: str = "append") -> "DataFrame":
        """Write rows into a SQL table through a DB-API connection or a
        zero-arg connection factory (reference: DataFrame.write_sql via
        SQLAlchemy; here plain DB-API keeps it dependency-free — sqlite3
        from the stdlib works out of the box)."""
        from ..io.sql_writer import write_sql

        return write_sql(self, table_name, connection, mode)

    def write_lance(self, uri: str, mode: str = "create", **kwargs) -> "DataFrame":
        """Write a Lance dataset (requires the `lance` package, like the
        reference's DataFrame.write_lance)."""
        try:
            import lance
        except ImportError as e:
            raise ImportError(
                "write_lance requires the 'lance' package (pip install pylance)"
            ) from e
        table = self.to_arrow()
        lance.write_dataset(table, uri, mode=mode, **kwargs)
        import daft_tpu

        return daft_tpu.from_pydict({"uri": [uri], "rows": [table.num_rows]})

    def write_huggingface(self, repo_id: str, **kwargs) -> "DataFrame":
        """Push to a HuggingFace dataset repo (requires `huggingface_hub`,
        like the reference's DataFrame.write_huggingface)."""
        try:
            from huggingface_hub import HfApi  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "write_huggingface requires the 'huggingface_hub' package"
            ) from e
        raise NotImplementedError(
            "huggingface_hub is available but this build has no network egress; "
            "use write_parquet + huggingface_hub.upload_file")

    def skip_existing(self, existing_path, key_column: Union[str, List[str]],
                      file_format: str = "parquet") -> "DataFrame":
        """Drop rows whose key already appears in previously-written output
        (reference: DataFrame.skip_existing — resume semantics for bulk
        writes). Reads only the key column(s) from existing_path."""
        import daft_tpu

        keys = [key_column] if isinstance(key_column, str) else list(key_column)
        paths = existing_path if isinstance(existing_path, list) else [existing_path]
        readers = {"parquet": daft_tpu.read_parquet, "csv": daft_tpu.read_csv,
                   "json": daft_tpu.read_json}
        if file_format not in readers:
            raise ValueError(f"unsupported file_format {file_format!r}")
        import glob as _glob
        import os as _os

        existing = None
        for p in paths:
            if _os.path.isdir(p):
                ext = "json" if file_format == "json" else file_format
                files = sorted(_glob.glob(_os.path.join(p, f"**/*.{ext}"),
                                          recursive=True))
            else:
                files = [p] if _os.path.exists(p) else []
            for fp in files:
                part = readers[file_format](fp).select(*[col(k) for k in keys])
                existing = part if existing is None else existing.concat(part)
        if existing is None:
            return self
        kexprs = [col(k) for k in keys]
        return self.join(existing.distinct(), left_on=kexprs, right_on=kexprs,
                         how="anti")

    # ---- external-framework conversions -------------------------------------------
    def to_ray_dataset(self):
        """Convert to a Ray Dataset (requires `ray`, like the reference's
        DataFrame.to_ray_dataset)."""
        try:
            import ray.data
        except ImportError as e:
            raise ImportError("to_ray_dataset requires the 'ray' package") from e
        return ray.data.from_arrow(self.to_arrow())

    def to_dask_dataframe(self, npartitions: Optional[int] = None):
        """Convert to a Dask DataFrame (requires `dask`, like the reference's
        DataFrame.to_dask_dataframe)."""
        try:
            import dask.dataframe as dd
        except ImportError as e:
            raise ImportError("to_dask_dataframe requires the 'dask' package") from e
        return dd.from_pandas(self.to_pandas(),
                              npartitions=npartitions or max(self.num_partitions(), 1))

    def _write(self, info) -> "DataFrame":
        return DataFrame(self._builder.write(info)).collect()

    # ---- misc --------------------------------------------------------------------
    def num_partitions(self) -> int:
        if self._result is not None:
            return len(self._result)
        return 1


class GroupedDataFrame:
    def __init__(self, df: DataFrame, group_by: List[Expression]):
        self._df = df
        self._group_by = group_by

    def agg(self, *aggs: Expression) -> DataFrame:
        return self._df._next(self._df._builder.aggregate(_flatten_aggs(aggs), self._group_by))

    def sum(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[_to_expr(c).sum() for c in cols])

    def mean(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[_to_expr(c).mean() for c in cols])

    def min(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[_to_expr(c).min() for c in cols])

    def max(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[_to_expr(c).max() for c in cols])

    def count(self, *cols: ColumnInput) -> DataFrame:
        if not cols:
            return self.agg(lit(1).count("all").alias("count"))
        return self.agg(*[_to_expr(c).count() for c in cols])

    def any_value(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[_to_expr(c).any_value() for c in cols])

    def agg_list(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[AggExpr("list", _to_expr(c)) for c in cols])

    def agg_concat(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[AggExpr("concat", _to_expr(c)) for c in cols])

    def agg_set(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[AggExpr("set", _to_expr(c)) for c in cols])

    list_agg = agg_list
    list_agg_distinct = agg_set

    def stddev(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[_to_expr(c).stddev() for c in cols])

    def var(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[_to_expr(c).var() for c in cols])

    def skew(self, *cols: ColumnInput) -> DataFrame:
        return self.agg(*[_to_expr(c).skew() for c in cols])

    def string_agg(self, *cols: ColumnInput, delimiter: str = "") -> DataFrame:
        names = [_to_expr(c).name() for c in cols]
        gnames = [e.name() for e in self._group_by]
        out = self.agg(*[AggExpr("list", _to_expr(c)).alias(n)
                         for c, n in zip(cols, names)])
        from ..expressions import col as _col

        keep = [_col(n) for n in gnames]
        keep += [_col(n).list.join(delimiter).alias(n) for n in names]
        return out.select(*keep)

    def map_groups(self, udf_expr: Expression) -> DataFrame:
        """Apply a UDF to each group's rows; the UDF may emit any number of
        rows per group (reference: GroupedDataFrame.map_groups)."""
        from ..plan import logical as lp

        df = self._df
        plan = lp.MapGroups(df._builder.plan, self._group_by, udf_expr)
        return df._next(df._builder._next(plan))


def _flatten_aggs(aggs) -> List[Expression]:
    out: List[Expression] = []
    for a in aggs:
        if isinstance(a, (list, tuple)):
            out.extend(_flatten_aggs(a))
        else:
            out.append(a)
    return out


def _format_table(mp: MicroPartition, schema: Schema, max_width: int = 30) -> str:
    d = mp.to_pydict()
    names = schema.column_names()
    dtypes = [str(schema[n].dtype) for n in names]
    rows = mp.num_rows

    def fmt(v) -> str:
        s = "None" if v is None else str(v)
        return s if len(s) <= max_width else s[: max_width - 1] + "…"

    cols = [[fmt(v) for v in d[n]] for n in names]
    widths = [max(len(n), len(t), *(len(v) for v in c) if c else (0,)) for n, t, c in zip(names, dtypes, cols)]
    sep = "╭" + "┬".join("─" * (w + 2) for w in widths) + "╮"
    mid = "├" + "┼".join("─" * (w + 2) for w in widths) + "┤"
    bot = "╰" + "┴".join("─" * (w + 2) for w in widths) + "╯"
    lines = [sep]
    lines.append("│" + "│".join(f" {n:<{w}} " for n, w in zip(names, widths)) + "│")
    lines.append("│" + "│".join(f" {t:<{w}} " for t, w in zip(dtypes, widths)) + "│")
    lines.append(mid)
    for i in range(rows):
        lines.append("│" + "│".join(f" {c[i]:<{w}} " for c, w in zip(cols, widths)) + "│")
    lines.append(bot)
    lines.append(f"(Showing {rows} rows)")
    return "\n".join(lines)
