"""Task model for the distributed engine.

Reference parity: src/daft-distributed/src/scheduling/task.rs:212 (SwordfishTask
= serialized LocalPhysicalPlan sub-DAG + SchedulingStrategy) and task.rs:165
(Spread / WorkerAffinity scheduling strategies).
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple


@dataclass(frozen=True)
class Spread:
    """Run anywhere; scheduler picks the worker with the most free slots."""


@dataclass(frozen=True)
class WorkerAffinity:
    """Prefer (soft) or require (hard) a specific worker — used for cached
    shuffle locality and stateful actor pools."""

    worker_id: str
    hard: bool = False


@dataclass
class SubPlanTask:
    """A serialized physical sub-plan to run on one worker.

    The plan's leaves are InMemoryScan (inline data) or ShuffleRead nodes; a
    plan rooted at ShuffleWrite produces shuffle files instead of inline
    results.
    """

    task_id: str
    plan_blob: bytes
    strategy: Any = field(default_factory=Spread)
    priority: int = 0
    # workers that already failed this task (reference: scheduler re-queues with
    # the failed worker excluded)
    excluded_workers: Tuple[str, ...] = ()

    @classmethod
    def from_plan(cls, task_id: str, plan, strategy=None, priority: int = 0) -> "SubPlanTask":
        return cls(task_id=task_id, plan_blob=pickle.dumps(plan),
                   strategy=strategy or Spread(), priority=priority)

    def plan(self):
        return pickle.loads(self.plan_blob)


@dataclass
class TaskResult:
    task_id: str
    worker_id: str
    # inline result partitions (pickled MicroPartitions); empty for shuffle writes
    partitions: List[Any] = field(default_factory=list)
    rows: int = 0
    error: Optional[str] = None
    error_tb: Optional[str] = None
