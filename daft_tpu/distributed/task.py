"""Task model for the distributed engine.

Reference parity: src/daft-distributed/src/scheduling/task.rs:212 (SwordfishTask
= serialized LocalPhysicalPlan sub-DAG + SchedulingStrategy) and task.rs:165
(Spread / WorkerAffinity scheduling strategies).
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple


@dataclass(frozen=True)
class Spread:
    """Run anywhere; scheduler picks the worker with the most free slots."""


@dataclass(frozen=True)
class WorkerAffinity:
    """Prefer (soft) or require (hard) a specific worker — used for cached
    shuffle locality and stateful actor pools."""

    worker_id: str
    hard: bool = False


@dataclass
class SubPlanTask:
    """A serialized physical sub-plan to run on one worker.

    The plan's leaves are InMemoryScan (inline data) or ShuffleRead nodes; a
    plan rooted at ShuffleWrite produces shuffle files instead of inline
    results.
    """

    task_id: str
    plan_blob: bytes
    strategy: Any = field(default_factory=Spread)
    priority: int = 0
    # workers that already failed this task (reference: scheduler re-queues with
    # the failed worker excluded)
    excluded_workers: Tuple[str, ...] = ()
    # pipeline stage this task belongs to (planner-assigned, e.g. "shuffle:0")
    stage_id: str = ""
    # query trace context stamped at dispatch (same _trace_id/_span_id scheme
    # as observability/otlp.py) — worker-side task/operator spans join the
    # driver query's trace through these
    trace_id: str = ""
    parent_span_id: str = ""
    # run the sub-plan under a StatsCollector and ship stats back
    collect_stats: bool = False
    # driver time.time() when the task entered the scheduler (queue-wait base)
    submitted_at: float = 0.0
    # residency fingerprint: (stable_slot_key, est_bytes) pairs for the device
    # planes this sub-plan would probe (distributed/affinity.py). The scheduler
    # intersects it with worker heartbeat digests for cache-affinity placement;
    # () = no device-cacheable inputs (plain spread scheduling).
    rfingerprint: Tuple[Tuple[int, int], ...] = ()

    @classmethod
    def from_plan(cls, task_id: str, plan, strategy=None, priority: int = 0,
                  stage_id: str = "", rfingerprint: Tuple = ()) -> "SubPlanTask":
        # cloudpickle serializes by VALUE anything a fresh worker process
        # cannot import (custom DataSource tasks defined in __main__, a
        # notebook, or a test module) — the reference ships sub-plans the same
        # way (vendored cloudpickle). Workers unpickle with plain pickle.
        try:
            import cloudpickle

            blob = cloudpickle.dumps(plan)
        except ImportError:
            blob = pickle.dumps(plan)
        return cls(task_id=task_id, plan_blob=blob,
                   strategy=strategy or Spread(), priority=priority,
                   stage_id=stage_id, rfingerprint=tuple(rfingerprint))

    def plan(self):
        return pickle.loads(self.plan_blob)


@dataclass
class TaskResult:
    task_id: str
    worker_id: str
    # inline result partitions (pickled MicroPartitions); empty for shuffle writes
    partitions: List[Any] = field(default_factory=list)
    rows: int = 0
    error: Optional[str] = None
    error_tb: Optional[str] = None
    # structured error classification for recoverable failures: "" (generic),
    # "shuffle_data_lost" (error_data: {shuffle_id, map_ids}) or
    # "shuffle_peer_unreachable" (error_data: {shuffle_id}). The pool
    # re-raises these as their typed exceptions so the planner's recovery
    # path can regenerate lost map outputs instead of failing the query.
    error_kind: str = ""
    error_data: Optional[dict] = None
    # shuffle map-output lineage records produced while this task ran
    # (shuffle.py _note_map_output: {shuffle_id, map_id, rows-per-partition,
    # paths}); ALWAYS populated for ShuffleWrite tasks, independent of
    # collect_stats — the driver derives each reduce partition's
    # expected_maps from the rows lists (correctness, not telemetry)
    map_outputs: Tuple[dict, ...] = ()
    # ---- runtime stats (populated when the task asked for collect_stats) ---------
    bytes_out: int = 0
    exec_seconds: float = 0.0
    started_at: float = 0.0          # worker unix time at execution start
    span_id: str = ""                # worker task span id within the stamped trace
    # per-operator stats from the worker's StatsCollector (OperatorStats tuples)
    op_stats: Tuple[Any, ...] = ()
    # shuffle volume recorded while this task ran (ShuffleRecorder.as_dict())
    shuffle: Optional[dict] = None
    # worker metrics-registry counter deltas over this task's execution
    # (device_stage_batches, dispatch_coalesced, hbm_* ...): the driver's
    # per-operator stats alone cannot show WHICH engine path a worker took —
    # a device-leased worker's dispatches land here. The trace mirrors the
    # device/coalescing subset (trace._MIRRORED_ENGINE_COUNTERS) into the
    # driver registry for EXPLAIN ANALYZE / QueryEnd.metrics; hbm_* stays
    # per-process (worker HBM telemetry flows via heartbeats instead).
    engine_counters: Optional[dict] = None
    # timeline profiler spans recorded while this task ran (SpanRecorder
    # dicts: device dispatch / h2d / d2h / coalescer flushes / shuffle
    # fetches, worker-clock unix timestamps). QueryTrace aligns them to the
    # driver clock via heartbeat-estimated offsets for the Chrome trace
    # export; bounded by the recorder cap, empty when nothing coarse ran.
    spans: Tuple[dict, ...] = ()
