"""Driver-side distributed query trace: per-stage task stats, shuffle volume,
worker heartbeats.

Reference parity: the Flotilla scheduler's per-task stats + subscriber
callbacks (daft/runners/flotilla.py stats path) joined to the local engine's
runtime_stats vocabulary. The WorkerPool records every finished task here
(timing measured where it happens: queue wait on the driver, exec wall time on
the worker), the runner emits the accumulated records to subscribers at query
end, and DataFrame.explain_analyze() renders the per-stage skew table.
"""

from __future__ import annotations

import statistics
import threading
import time
from typing import Dict, List, Optional

from ..config import _env_float
from ..observability.events import ShuffleStats, TaskStats, WorkerHeartbeat
from ..observability.metrics import registry
from ..observability.otlp import _span_id, _trace_id

# straggler detection threshold: a task is flagged when its exec time exceeds
# k x its stage's median (the detection half of speculative re-execution)
_DEFAULT_STRAGGLER_K = 2.0


def straggler_threshold() -> float:
    return _env_float("DAFT_TPU_STRAGGLER_K", _DEFAULT_STRAGGLER_K)


# Worker engine counters mirrored into the driver registry per finished task
# (device-path + batching attribution; shuffle volume arrives via
# result.shuffle, hbm gauges stay per-process).
_MIRRORED_ENGINE_COUNTERS = (
    "device_stage_batches", "device_grouped_batches", "device_stage_runs",
    "device_join_batches", "device_topn_runs", "mesh_grouped_runs",
    "dispatch_coalesced", "coalesce_morsels_in", "bucket_fill_rows",
    "bucket_capacity_rows", "morsel_resize",
)


class QueryTrace:
    """Accumulates one distributed query's task/shuffle/heartbeat records.

    Thread-safe: the pool's dispatch loop appends while the driver thread may
    concurrently render (explain_analyze on a partially-streamed query).
    """

    def __init__(self, query_id: str):
        self.query_id = query_id
        self.trace_id = _trace_id(query_id) if query_id else ""
        self.root_span_id = _span_id(query_id, "query") if query_id else ""
        self.started_wall = time.time()   # trace epoch for the timeline export
        self._lock = threading.Lock()
        self.tasks: List[TaskStats] = []
        self.heartbeats: List[WorkerHeartbeat] = []
        # task_id -> worker-clock timeline spans shipped in the TaskResult
        # (kept off TaskStats so event-log task records stay flat/grep-able)
        self.task_spans: Dict[str, List[dict]] = {}
        # stage_id -> accumulated shuffle dict (insertion-ordered)
        self._shuffle: Dict[str, dict] = {}
        self._stage_order: List[str] = []
        # stage_id -> scheduler placement totals (affinity hits/misses,
        # bytes avoided, head-of-line skips) — see Scheduler.placement_stats
        self._placement: Dict[str, Dict[str, int]] = {}
        # fault-recovery totals for this query (worker_failures,
        # tasks_requeued, maps_regenerated) — the pool's liveness monitor and
        # the planner's regeneration loop note into these; EXPLAIN ANALYZE
        # renders the "recovery:" line when any is nonzero
        self._recovery: Dict[str, int] = {}

    # ---- recording (called by WorkerPool.run_tasks) ------------------------------
    def record_task(self, task, result, dispatched_at: float) -> None:
        """One successfully finished task: join driver-side queueing times with
        the worker-side execution record shipped in the TaskResult."""
        queue_wait = max(dispatched_at - task.submitted_at, 0.0) \
            if task.submitted_at else 0.0
        sched_lat = max(result.started_at - dispatched_at, 0.0) \
            if result.started_at else 0.0
        ts = TaskStats(
            stage_id=task.stage_id or "stage",
            task_id=task.task_id,
            worker_id=result.worker_id,
            queue_wait_s=queue_wait,
            schedule_latency_s=sched_lat,
            exec_s=result.exec_seconds,
            rows_out=result.rows,
            bytes_out=result.bytes_out,
            retries=len(task.excluded_workers),
            started_at=result.started_at,
            trace_id=task.trace_id,
            span_id=result.span_id,
            parent_span_id=task.parent_span_id,
            operator_stats=tuple(result.op_stats),
            engine_counters=tuple(sorted((result.engine_counters or {}).items())),
        )
        with self._lock:
            self.tasks.append(ts)
            if result.spans:
                self.task_spans[ts.task_id] = list(result.spans)
            if ts.stage_id not in self._shuffle:
                self._shuffle[ts.stage_id] = {}
                self._stage_order.append(ts.stage_id)
            if result.shuffle:
                acc = self._shuffle[ts.stage_id]
                for k, v in result.shuffle.items():
                    if k == "fetch_fanin":
                        # a max across tasks, not a volume — summing would
                        # report nonsense parallelism
                        acc[k] = max(acc.get(k, 0), v)
                    else:
                        acc[k] = acc.get(k, 0) + v
        if result.shuffle:
            # mirror into the driver's registry so the per-query metrics diff
            # (QueryEnd.metrics, bench snapshot) carries cluster-wide volume
            for k in ("bytes_written", "rows_written", "bytes_fetched",
                      "rows_fetched"):
                v = result.shuffle.get(k, 0)
                if v:
                    registry().inc(f"shuffle_{k}", int(v))
            # wire/logical + overlap attribution (workers count these in
            # THEIR registries; re-home them so the driver-side per-query
            # diff can assert compression ratio and transfer overlap)
            for src, dst in (("bytes_written", "shuffle_logical_bytes"),
                             ("wire_bytes_written", "shuffle_wire_bytes")):
                v = result.shuffle.get(src, 0)
                if v:
                    registry().inc(dst, int(v))
            for src, dst in (("fetch_seconds", "shuffle_fetch_seconds"),
                             ("fetch_wall_seconds", "shuffle_fetch_wall_seconds"),
                             ("overlap_seconds", "shuffle_overlap_seconds")):
                v = result.shuffle.get(src, 0.0)
                if v:
                    registry().inc(dst, float(v))
        if result.engine_counters:
            # device-path attribution crosses the process boundary the same
            # way: a device-leased worker's dispatches/coalescing land in the
            # driver's per-query diff (distributed EXPLAIN ANALYZE engine
            # counters, QueryEnd.metrics, bench snapshot). Curated list —
            # shuffle counters are mirrored above from result.shuffle, and
            # gauges don't sum across processes.
            for k in _MIRRORED_ENGINE_COUNTERS:
                v = result.engine_counters.get(k, 0)
                if v:
                    registry().inc(k, int(v))

    def add_heartbeat(self, hb: dict) -> None:
        rec = WorkerHeartbeat(
            worker_id=hb.get("worker_id", "?"),
            ts=hb.get("ts", 0.0),
            busy_slots=hb.get("busy_slots", 0),
            total_slots=hb.get("total_slots", 1),
            tasks_completed=hb.get("tasks_completed", 0),
            tasks_failed=hb.get("tasks_failed", 0),
            rss_bytes=hb.get("rss_bytes", 0),
            uptime_s=hb.get("uptime_s", 0.0),
            hbm_bytes=hb.get("hbm_bytes_resident", 0),
            hbm_h2d_bytes=hb.get("hbm_h2d_bytes", 0),
            hbm_digest_entries=len(hb.get("hbm_digest") or ()),
            recv_ts=hb.get("recv_ts", 0.0),
            dead=bool(hb.get("dead", False)),
            death_reason=hb.get("death_reason", ""),
        )
        with self._lock:
            self.heartbeats.append(rec)

    def clock_offsets(self) -> Dict[str, float]:
        """Per-worker clock offset estimate (driver = worker + offset).

        Cristian-style one-way bound from heartbeat round trips: every beat
        gives recv_ts(driver) - ts(worker) = true offset + transit; the MIN
        over a query's beats is the tightest bound (transit >= 0). On
        same-host workers (shared clock) this converges to the send/recv
        latency, typically sub-millisecond. Workers without beats map to 0.
        """
        with self._lock:
            hbs = list(self.heartbeats)
        out: Dict[str, float] = {}
        for hb in hbs:
            if hb.ts <= 0 or hb.recv_ts <= 0:
                continue
            d = hb.recv_ts - hb.ts
            if hb.worker_id not in out or d < out[hb.worker_id]:
                out[hb.worker_id] = d
        return out

    def note_placement(self, stage_id: str, stats: Dict[str, int]) -> None:
        """Record one stage's scheduler placement totals (called by the pool
        when the stage drains)."""
        with self._lock:
            self._placement[stage_id] = dict(stats)

    def note_recovery(self, key: str, n: int = 1) -> None:
        """Accumulate one fault-recovery event (worker_failures /
        tasks_requeued / maps_regenerated) into this query's totals."""
        with self._lock:
            self._recovery[key] = self._recovery.get(key, 0) + n

    def recovery_totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._recovery)

    # ---- aggregation -------------------------------------------------------------
    def shuffle_stats(self) -> List[ShuffleStats]:
        with self._lock:
            out = []
            for sid in self._stage_order:
                acc = self._shuffle[sid]
                if not acc:
                    continue
                out.append(ShuffleStats(
                    stage_id=sid,
                    bytes_written=int(acc.get("bytes_written", 0)),
                    rows_written=int(acc.get("rows_written", 0)),
                    partitions_written=int(acc.get("partitions_written", 0)),
                    bytes_fetched=int(acc.get("bytes_fetched", 0)),
                    rows_fetched=int(acc.get("rows_fetched", 0)),
                    fetch_seconds=float(acc.get("fetch_seconds", 0.0)),
                    fetch_requests=int(acc.get("fetch_requests", 0)),
                    wire_bytes_written=int(acc.get("wire_bytes_written", 0)),
                    fetch_wall_seconds=float(acc.get("fetch_wall_seconds", 0.0)),
                    overlap_seconds=float(acc.get("overlap_seconds", 0.0)),
                    fetch_fanin=int(acc.get("fetch_fanin", 0)),
                ))
            return out

    def stage_summaries(self) -> List[dict]:
        """Per-stage rollup in execution order: task count, exec-time skew
        (min/median/max), rows, queue wait, shuffle volume."""
        with self._lock:
            by_stage: Dict[str, List[TaskStats]] = {}
            for t in self.tasks:
                by_stage.setdefault(t.stage_id, []).append(t)
            order = list(self._stage_order)
            shuffle = {k: dict(v) for k, v in self._shuffle.items()}
            placement = {k: dict(v) for k, v in self._placement.items()}
        out = []
        for sid in order:
            tasks = by_stage.get(sid, [])
            if not tasks:
                continue
            times = sorted(t.exec_s for t in tasks)
            sh = shuffle.get(sid, {})
            pl = placement.get(sid, {})
            out.append({
                "affinity_hits": int(pl.get("affinity_hits", 0)),
                "affinity_misses": int(pl.get("affinity_misses", 0)),
                "sched_bytes_avoided": int(pl.get("bytes_avoided", 0)),
                "stage_id": sid,
                "tasks": len(tasks),
                "workers": len({t.worker_id for t in tasks}),
                "retries": sum(t.retries for t in tasks),
                "rows_out": sum(t.rows_out for t in tasks),
                "bytes_out": sum(t.bytes_out for t in tasks),
                "queue_wait_s": sum(t.queue_wait_s for t in tasks),
                "min_s": times[0],
                "median_s": statistics.median(times),
                "max_s": times[-1],
                "shuffle_bytes_written": int(sh.get("bytes_written", 0)),
                "shuffle_bytes_fetched": int(sh.get("bytes_fetched", 0)),
                "shuffle_wire_bytes": int(sh.get("wire_bytes_written", 0)),
                "shuffle_fetch_cum_s": float(sh.get("fetch_seconds", 0.0)),
                "shuffle_fetch_wall_s": float(sh.get("fetch_wall_seconds", 0.0)),
                "shuffle_overlap_s": float(sh.get("overlap_seconds", 0.0)),
                "shuffle_fetch_fanin": int(sh.get("fetch_fanin", 0)),
            })
        return out

    def worker_summary(self) -> List[dict]:
        with self._lock:
            tasks = list(self.tasks)
            hbs = list(self.heartbeats)
        by_worker: Dict[str, dict] = {}
        for t in tasks:
            w = by_worker.setdefault(t.worker_id,
                                     {"tasks": 0, "exec_s": 0.0, "rows": 0})
            w["tasks"] += 1
            w["exec_s"] += t.exec_s
            w["rows"] += t.rows_out
        for hb in hbs:
            w = by_worker.setdefault(hb.worker_id,
                                     {"tasks": 0, "exec_s": 0.0, "rows": 0})
            w["rss_bytes"] = hb.rss_bytes      # latest wins (list is in order)
            w["heartbeats"] = w.get("heartbeats", 0) + 1
        return [{"worker_id": k, **v} for k, v in sorted(by_worker.items())]

    def straggler_report(self, threshold: Optional[float] = None) -> List[dict]:
        """Tasks whose exec time exceeded `threshold` x their stage median —
        the detection half of speculative re-execution (the scheduler can act
        on exactly this list). Stages need >= 2 tasks for a meaningful
        median; threshold defaults from DAFT_TPU_STRAGGLER_K (2.0)."""
        k = threshold if threshold is not None else straggler_threshold()
        with self._lock:
            by_stage: Dict[str, List[TaskStats]] = {}
            for t in self.tasks:
                by_stage.setdefault(t.stage_id, []).append(t)
        out = []
        for sid, tasks in by_stage.items():
            if len(tasks) < 2:
                continue
            med = statistics.median(t.exec_s for t in tasks)
            if med <= 1e-9:
                continue
            for t in tasks:
                if t.exec_s > k * med:
                    out.append({
                        "stage_id": sid, "task_id": t.task_id,
                        "worker_id": t.worker_id, "exec_s": t.exec_s,
                        "median_s": med, "ratio": t.exec_s / med,
                    })
        out.sort(key=lambda r: -r["ratio"])
        return out

    # ---- timeline export ---------------------------------------------------------
    def to_chrome_trace(self, driver_ops=None, driver_spans=None,
                        total_seconds: Optional[float] = None) -> dict:
        """The query as Chrome trace-event JSON (open in Perfetto / chrome://
        tracing): driver lane (query + stage windows + operator slices) and
        one process per worker with a task lane, an operator lane, and a
        device/io lane of REAL wall-clock spans (dispatch, h2d/d2h, coalescer
        flushes, shuffle fetches). Worker timestamps are re-aligned onto the
        driver clock via heartbeat-estimated offsets (clock_offsets).

        Operator slices have no per-batch timestamps by design (recording
        them would tax the hot path), so each lane lays its operators out
        SEQUENTIALLY from the lane's start — slice WIDTH is the attributed
        self time, position within the lane is schematic. Stall slices
        (starve/blocked) ride a separate lane the same way. Device/io spans
        are true wall-clock intervals.
        """
        epoch = self.started_wall
        offsets = self.clock_offsets()
        events: List[dict] = []
        # trace-event pids/tids are integers; names arrive via "M" metadata.
        # driver = pid 0; workers 1..N. Lane (tid) layout per process:
        # 0 query/tasks, 1 stages (driver only), 2 operators, 3 stalls,
        # 4 device/io
        T_MAIN, T_STAGES, T_OPS, T_STALLS, T_IO = 0, 1, 2, 3, 4

        def ev(name, cat, pid, tid, ts_s, dur_s, args=None):
            e = {"name": name, "cat": cat, "ph": "X", "pid": pid, "tid": tid,
                 "ts": round(ts_s * 1e6, 1),
                 "dur": round(max(dur_s, 0.0) * 1e6, 1)}
            if args:
                e["args"] = args
            events.append(e)

        def meta(pid, kind, label, tid=None):
            e = {"name": kind, "ph": "M", "pid": pid, "args": {"name": label}}
            if tid is not None:
                e["tid"] = tid
            events.append(e)

        def name_lanes(pid, main_label):
            meta(pid, "thread_name", main_label, T_MAIN)
            meta(pid, "thread_name", "operators", T_OPS)
            meta(pid, "thread_name", "stalls", T_STALLS)
            meta(pid, "thread_name", "device/io", T_IO)

        def op_lanes(ops, pid, start_s):
            """Sequential operator + stall lanes for one process/task."""
            cursor = start_s
            for s in ops:
                # slice width = compute when the stall split is populated
                # (stall lanes draw starve/blocked separately — a fully-
                # starved operator must not double-draw its wait); whole
                # self time only for split-less legacy records
                split = (s.compute_seconds + s.starve_seconds
                         + s.blocked_seconds)
                width = s.compute_seconds if split > 0 else s.seconds
                ev(s.name, "operator", pid, T_OPS, cursor, width,
                   {"node_id": s.node_id, "rows_out": s.rows_out,
                    "batches_out": s.batches_out,
                    "compute_s": round(s.compute_seconds, 6),
                    "starve_s": round(s.starve_seconds, 6),
                    "blocked_s": round(s.blocked_seconds, 6)})
                cursor += width
            cursor = start_s
            for s in ops:
                if s.starve_seconds > 0:
                    ev(f"starve:{s.name}", "stall", pid, T_STALLS, cursor,
                       s.starve_seconds)
                    cursor += s.starve_seconds
                if s.blocked_seconds > 0:
                    ev(f"blocked:{s.name}", "stall", pid, T_STALLS, cursor,
                       s.blocked_seconds)
                    cursor += s.blocked_seconds

        def raw_spans(spans, pid, offset):
            for sp in spans:
                ev(sp["name"], sp.get("cat", "span"), pid, T_IO,
                   sp["ts"] + offset - epoch, sp["dur"], sp.get("args"))

        with self._lock:
            tasks = list(self.tasks)
            task_spans = {k: list(v) for k, v in self.task_spans.items()}

        worker_pid = {wid: i + 1 for i, wid in
                      enumerate(sorted({t.worker_id for t in tasks}))}

        meta(0, "process_name", "driver")
        name_lanes(0, "query")
        meta(0, "thread_name", "stages", T_STAGES)
        end = epoch + (total_seconds or 0.0)
        for t in tasks:
            off = offsets.get(t.worker_id, 0.0)
            if t.started_at:
                end = max(end, t.started_at + off + t.exec_s)
        ev(f"query:{self.query_id or 'local'}", "query", 0, T_MAIN,
           0.0, end - epoch, {"query_id": self.query_id})

        # stage windows on the driver lane: [first task start, last task end]
        by_stage: Dict[str, List[TaskStats]] = {}
        for t in tasks:
            by_stage.setdefault(t.stage_id, []).append(t)
        for sid, sts in by_stage.items():
            timed = [t for t in sts if t.started_at]
            if not timed:
                continue
            s0 = min(t.started_at + offsets.get(t.worker_id, 0.0)
                     for t in timed)
            s1 = max(t.started_at + offsets.get(t.worker_id, 0.0) + t.exec_s
                     for t in timed)
            ev(f"stage:{sid}", "stage", 0, T_STAGES, s0 - epoch, s1 - s0,
               {"tasks": len(sts)})

        if driver_ops:
            op_lanes(driver_ops, 0, 0.0)
        if driver_spans:
            raw_spans(driver_spans, 0, 0.0)

        stragglers = {r["task_id"] for r in self.straggler_report()}
        for wid, pid in worker_pid.items():
            meta(pid, "process_name", f"worker {wid}")
            name_lanes(pid, "tasks")
        for t in tasks:
            pid = worker_pid[t.worker_id]
            off = offsets.get(t.worker_id, 0.0)
            t0 = (t.started_at + off - epoch) if t.started_at else 0.0
            ev(f"task:{t.task_id}", "task", pid, T_MAIN, t0, t.exec_s,
               {"stage_id": t.stage_id, "worker_id": t.worker_id,
                "rows_out": t.rows_out, "retries": t.retries,
                "queue_wait_s": round(t.queue_wait_s, 6),
                "straggler": t.task_id in stragglers})
            if t.operator_stats:
                op_lanes(t.operator_stats, pid, t0)
            if t.task_id in task_spans:
                raw_spans(task_spans[t.task_id], pid, off)

        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "query_id": self.query_id,
                "trace_id": self.trace_id,
                "trace_epoch_unix_s": epoch,
                "clock_offsets_s": offsets,
                "workers": {w: p for w, p in worker_pid.items()},
            },
        }

    # ---- rendering ---------------------------------------------------------------
    def render(self) -> str:
        """The distributed EXPLAIN ANALYZE section: stage DAG rollup with task
        skew (min/median/max task time) and shuffle volumes, then per-worker
        attribution."""
        stages = self.stage_summaries()
        if not stages:
            return "(no distributed stages ran)"
        lines = [f"{'stage':<22} {'tasks':>5} {'rows out':>12} "
                 f"{'min/median/max task':>24} {'queue wait':>10} "
                 f"{'shuffle w':>10} {'shuffle r':>10}"]
        for s in stages:
            skew = (f"{s['min_s']*1e3:.1f}/{s['median_s']*1e3:.1f}/"
                    f"{s['max_s']*1e3:.1f}ms")
            lines.append(
                f"{s['stage_id']:<22} {s['tasks']:>5} {s['rows_out']:>12} "
                f"{skew:>24} {s['queue_wait_s']*1e3:>8.1f}ms "
                f"{_fmt_bytes(s['shuffle_bytes_written']):>10} "
                f"{_fmt_bytes(s['shuffle_bytes_fetched']):>10}")
            if s["retries"]:
                lines.append(f"  {'':<20} ({s['retries']} task retries)")
            if s["shuffle_wire_bytes"] and s["shuffle_bytes_written"]:
                # per-stage compression ratio: wire bytes on disk/socket vs
                # logical Arrow buffer bytes
                ratio = s["shuffle_wire_bytes"] / s["shuffle_bytes_written"]
                lines.append(
                    f"  {'':<20} (compression: "
                    f"{_fmt_bytes(s['shuffle_wire_bytes'])} wire / "
                    f"{_fmt_bytes(s['shuffle_bytes_written'])} logical = "
                    f"{ratio:.2f}x)")
            if s["shuffle_fetch_cum_s"]:
                # fetch_seconds is CUMULATIVE in-flight time (over-counts the
                # wall-clock transfer window once requests overlap); the wall
                # window and the overlap bought by the pipelined fan-in are
                # labeled separately
                lines.append(
                    f"  {'':<20} (fetch: "
                    f"{s['shuffle_fetch_cum_s']*1e3:.1f}ms cumulative / "
                    f"{s['shuffle_fetch_wall_s']*1e3:.1f}ms wall, "
                    f"overlap {s['shuffle_overlap_s']*1e3:.1f}ms, "
                    f"fan-in {s['shuffle_fetch_fanin']})")
            if s["affinity_hits"] or s["affinity_misses"]:
                lines.append(
                    f"  {'':<20} (cache affinity: {s['affinity_hits']} hits, "
                    f"{s['affinity_misses']} misses, "
                    f"{_fmt_bytes(s['sched_bytes_avoided'])} transfer avoided)")
        recovery = self.recovery_totals()
        if recovery:
            pieces = []
            for key, label in (("worker_failures", "worker failures"),
                               ("tasks_requeued", "tasks requeued"),
                               ("maps_regenerated", "maps regenerated")):
                if recovery.get(key):
                    pieces.append(f"{recovery[key]} {label}")
            for key in sorted(recovery):
                if key not in ("worker_failures", "tasks_requeued",
                               "maps_regenerated"):
                    pieces.append(f"{recovery[key]} {key}")
            lines.append("")
            lines.append("recovery: " + ", ".join(pieces))
        stragglers = self.straggler_report()
        if stragglers:
            k = straggler_threshold()
            lines.append("")
            lines.append(f"stragglers (> {k:g}x stage median task time — "
                         "speculative re-execution candidates):")
            for r in stragglers:
                lines.append(
                    f"  {r['stage_id']}/{r['task_id']} on {r['worker_id']}: "
                    f"{r['exec_s']*1e3:.1f}ms vs median "
                    f"{r['median_s']*1e3:.1f}ms ({r['ratio']:.1f}x)")
        workers = self.worker_summary()
        if workers:
            lines.append("")
            lines.append(f"{'worker':<12} {'tasks':>5} {'busy':>10} "
                         f"{'rows out':>12} {'rss':>10} {'heartbeats':>10}")
            for w in workers:
                lines.append(
                    f"{w['worker_id']:<12} {w['tasks']:>5} "
                    f"{w['exec_s']*1e3:>8.1f}ms {w['rows']:>12} "
                    f"{_fmt_bytes(w.get('rss_bytes', 0)):>10} "
                    f"{w.get('heartbeats', 0):>10}")
        return "\n".join(lines)


def _fmt_bytes(n: int) -> str:
    if n <= 0:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024:
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}TiB"
