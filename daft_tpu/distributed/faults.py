"""Env-triggered fault-injection tripwires (the worker-side half of the
fault-injection harness; the driver-side helpers live in
tests/fault_injection.py).

A tripwire is armed entirely through the environment — the pool passes its
``env=`` dict into worker subprocesses, so a test arms one worker without
touching any production code path:

- ``DAFT_TPU_FAULT_POINT``: named injection point. Wired points:
    * ``shuffle_map``  — first batch appended by a MapOutputWriter
    * ``fetch``        — entry of a shuffle fetch fan-in (fetch_server client)
    * ``task_start``   — worker loop, before a task executes
    * ``task_sent``    — worker loop, after a task's RESULT was sent (the
      window where a map stage has completed but its files can still be lost)
- ``DAFT_TPU_FAULT_WORKER``: only trip in the worker whose id matches
  (workers export DAFT_TPU_WORKER_ID at startup); empty = any process.
- ``DAFT_TPU_FAULT_STAGE``: only trip when the active stage id starts with
  this prefix (e.g. ``shuffle``); empty = any stage.
- ``DAFT_TPU_FAULT_MODE``:
    * ``kill``       (default) — SIGKILL self: the hard crash
    * ``kill_lose``  — unlink the files the trip point reports (a map task's
      just-published shuffle outputs), then SIGKILL: simulates losing the
      whole host AND its shuffle storage, the per-worker-dir topology
    * ``stop``       — SIGSTOP self: the hung-but-not-dead worker the
      heartbeat-timeout detector must catch
    * ``delay:<s>``  — sleep s seconds, then continue: the 10x straggler
- ``DAFT_TPU_FAULT_ONCE_FILE``: sentinel path created atomically (O_EXCL)
  before tripping so a point fires at most once across every process sharing
  it (a regenerated map task must not re-trip forever).

Zero-overhead contract: call sites guard on the module constant ``ENABLED``
(False unless DAFT_TPU_FAULT_POINT was set when the process started), so the
production path pays one module-attribute read per coarse event.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Iterable, Optional

from ..utils.env import env_str

_POINT = env_str("DAFT_TPU_FAULT_POINT")

# read once at import: fault injection is armed per-process via spawn env
ENABLED = bool(_POINT)

# the stage id of the task this worker is currently executing (set by the
# worker loop): trip sites deep inside shuffle/fetch code don't carry the
# stage id, so the DAFT_TPU_FAULT_STAGE filter falls back to this
_STAGE = ""


def set_stage(stage_id: str) -> None:
    """Record the active task's stage id (worker loop, per task) so the
    stage filter works at trip points that only know a shuffle id."""
    global _STAGE
    _STAGE = stage_id


def maybe_trip(point: str, stage_id: str = "",
               paths: Optional[Iterable[str]] = None) -> None:
    """Fire the armed fault if `point` (and the worker/stage filters) match.
    Never raises — a misconfigured tripwire must not fail a healthy worker."""
    if point != _POINT:
        return
    want_worker = env_str("DAFT_TPU_FAULT_WORKER")
    if want_worker and env_str("DAFT_TPU_WORKER_ID") != want_worker:
        return
    want_stage = env_str("DAFT_TPU_FAULT_STAGE")
    if want_stage and not (stage_id or _STAGE).startswith(want_stage):
        return
    once = env_str("DAFT_TPU_FAULT_ONCE_FILE")
    if once:
        try:
            fd = os.open(once, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
        except FileExistsError:
            return  # already fired somewhere
        except OSError:
            return
    mode = env_str("DAFT_TPU_FAULT_MODE", "kill")
    if mode.startswith("delay:"):
        try:
            time.sleep(float(mode.split(":", 1)[1]))
        except ValueError:
            pass
        return
    if mode == "stop" and hasattr(signal, "SIGSTOP"):
        os.kill(os.getpid(), signal.SIGSTOP)
        return
    if mode == "kill_lose":
        for p in paths or ():
            try:
                os.unlink(p)
            except OSError:
                pass
    # "kill" and "kill_lose" end the same way: the unblockable hard crash
    os.kill(os.getpid(), signal.SIGKILL)
