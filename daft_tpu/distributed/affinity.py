"""Sub-plan residency fingerprints for cache-affinity scheduling.

The driver side of the distributed residency protocol (the worker side is
``ResidencyManager.digest()`` published in heartbeats): a fingerprint is the
set of stable slot keys a sub-plan's DEVICE path would probe, with estimated
device bytes per slot. Because stable keys are content-derived
(device/residency.py stable_slot_key — same column data + same slot shape →
same 64-bit key in any process), a key computed here from the plan the driver
is ABOUT to ship equals the key a worker registered when it executed the same
sub-plan before. ``Scheduler._pick_worker`` intersects the two and steers
repeat sub-plans to the worker already holding their planes (soft affinity in
the Delay-Scheduling tradition — never blocking on a saturated worker).

Mirrored slot shapes (must track the executors' registration sites):

- ``("col", bucket, f32)`` — Series.to_device_cached column planes fed by
  GroupedAggRun.feed_batch / FilterAggRun.feed_batch (f32 = not stage._use_f64,
  bucket = pad_bucket(batch rows)).
- ``("dictcodes", bucket)`` — grouped_stage.cached_dict_code_plane group-key
  dictionary planes (dict-keyed stages only).
- ``("udf_params",)`` — device-UDF model weight pytrees (ops/udf_stage.py),
  content-fingerprinted over the weight bytes so embedding sub-plans route
  to workers already holding the model warm.

Join-stage slots (index planes, packed dim matrices) are identity-dependent
(non-empty deps) and never rebind across processes, so they are deliberately
absent from both digests and fingerprints.

Everything here is advisory: any failure degrades to an empty fingerprint and
the scheduler's plain spread policy. A host-only plan (no Device* nodes) exits
before touching any device module — the zero-overhead contract.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..plan import physical as pp

# fingerprint length cap: a sub-plan probing more slots than this is scored on
# its hottest ones only (heartbeat digests are capped too)
MAX_FINGERPRINT_SLOTS = 128


def plan_fingerprint(plan) -> Tuple[Tuple[int, int], ...]:
    """(stable_slot_key, estimated_bytes) pairs for one sub-plan, or () when
    nothing in it can be device-resident (host-only plan, streaming leaves,
    python-object columns)."""
    try:
        device_nodes = [
            n for n in plan.walk()
            if isinstance(n, (pp.DeviceGroupedAgg, pp.DeviceFilterAgg,
                              pp.DeviceUdfProject))
        ]
        if not device_nodes:
            return ()
        slots: Dict[int, int] = {}
        for node in device_nodes:
            if isinstance(node, pp.DeviceUdfProject):
                _udf_slots(node, slots)
            else:
                _node_slots(node, slots)
            if len(slots) >= MAX_FINGERPRINT_SLOTS:
                break
        items = list(slots.items())[:MAX_FINGERPRINT_SLOTS]
        return tuple(items)
    except Exception:  # lint: ignore[broad-except] -- advisory: never fail task creation
        return ()


def _udf_slots(node, slots: Dict[int, int]) -> None:
    """The model-weight slots of a DeviceUdfProject: each part's
    content-derived key equals the key a worker registered when it uploaded
    the same weights (ops/udf_stage.py weight_slots), so repeat embedding
    sub-plans score onto workers whose HBM already holds the model warm.
    Loading the weights here is a once-per-process cost (the same load any
    execution pays)."""
    call = pp.device_udf_call(node.udf_expr)
    if call is None:
        return
    from ..ops.udf_stage import weight_slots

    for sk, est in weight_slots(call.func):
        slots[sk] = est


def _node_slots(node, slots: Dict[int, int]) -> None:
    from ..device.residency import stable_slot_key
    from ..expressions.expressions import Alias, ColumnRef
    from ..ops.stage import pad_bucket

    if isinstance(node, pp.DeviceGroupedAgg):
        from ..ops.grouped_stage import try_build_grouped_agg_stage

        stage = try_build_grouped_agg_stage(
            node.input.schema, node.predicate, node.groupby, node.aggregations)
    else:
        from ..ops.stage import try_build_filter_agg_stage

        stage = try_build_filter_agg_stage(
            node.input.schema, node.predicate, node.aggregations)
    if stage is None:
        return
    f32 = not stage._use_f64
    key_cols: List[str] = []
    if isinstance(node, pp.DeviceGroupedAgg) and getattr(stage, "dict_keys", False):
        for g in node.groupby:
            ref = g.child if isinstance(g, Alias) else g
            if isinstance(ref, ColumnRef):
                key_cols.append(ref.name())

    for scan in (n for n in node.walk() if isinstance(n, pp.InMemoryScan)):
        for part in scan.partitions:
            for b in part.batches:
                if b.num_rows == 0:
                    continue
                bucket = pad_bucket(b.num_rows)
                for cname in stage._input_cols:
                    _add_slot(slots, b, cname, ("col", bucket, f32),
                              bucket * 5, stable_slot_key)
                for cname in key_cols:
                    _add_slot(slots, b, cname, ("dictcodes", bucket),
                              bucket * 4, stable_slot_key)
                if len(slots) >= MAX_FINGERPRINT_SLOTS:
                    return


def _add_slot(slots: Dict[int, int], batch, cname: str, key: tuple,
              est_bytes: int, stable_slot_key) -> None:
    try:
        s = batch.get_column(cname)
    except Exception:  # lint: ignore[broad-except] -- column introduced above the scan
        return
    sk = stable_slot_key(s, key)
    if sk is not None:
        slots[sk] = est_bytes
