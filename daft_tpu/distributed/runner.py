"""Distributed runner: the engine's Flotilla-equivalent entry point.

Reference parity: daft/runners/flotilla.py:573 (FlotillaRunner) +
src/daft-distributed/src/plan/runner.rs:173 (PlanRunner.run_plan). Usage:

    import daft_tpu
    from daft_tpu.distributed import DistributedRunner
    daft_tpu.runners.set_runner(DistributedRunner(num_workers=4))

Distributable subtrees (scans/maps/joins/grouped aggs/repartitions) execute as
sub-plan tasks across spawn-based worker processes with disk-backed Arrow-IPC
shuffles; the driver executes whatever remains (sorts, windows, writes) over
the gathered results.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Iterator, Optional

from ..core.micropartition import MicroPartition
from ..plan.builder import LogicalPlanBuilder
from ..runners.native import Runner
from .planner import DistContext, localize
from .worker import WorkerPool


class DistributedRunner(Runner):
    def __init__(self, num_workers: int = 4, n_partitions: Optional[int] = None,
                 slots_per_worker: int = 1, shuffle_dir: Optional[str] = None,
                 shuffle_transport: str = "local",
                 max_workers: Optional[int] = None,
                 device_workers: int = 0):
        """shuffle_transport: "local" (reduce tasks read the shared shuffle
        directory — single-host fast path) or "socket" (reduce tasks fetch
        partitions from the HMAC-authenticated ShuffleFetchServer, the
        multi-host topology; reference flight_server.rs)."""
        if shuffle_transport not in ("local", "socket"):
            raise ValueError(f"unknown shuffle transport {shuffle_transport!r}")
        self.num_workers = num_workers
        self.max_workers = max_workers
        self.device_workers = device_workers
        self.n_partitions = n_partitions or num_workers
        self.slots_per_worker = slots_per_worker
        self.shuffle_transport = shuffle_transport
        self._shuffle_dir = shuffle_dir
        self._owns_shuffle_dir = shuffle_dir is None
        self._pool: Optional[WorkerPool] = None
        self._fetch_server = None
        # QueryTrace of the most recent traced run (distributed EXPLAIN ANALYZE)
        self.last_trace = None
        # concurrent run_iter callers (serving tier) must not race pool
        # creation; the pool itself is concurrent-caller safe once built
        import threading

        self._pool_init_lock = threading.Lock()

    def _ensure_pool(self) -> WorkerPool:
        with self._pool_init_lock:
            if self._pool is None:
                self._pool = WorkerPool(self.num_workers, self.slots_per_worker,
                                        max_workers=self.max_workers,
                                        device_workers=self.device_workers)
                if self._shuffle_dir is None:
                    self._shuffle_dir = tempfile.mkdtemp(prefix="daft_tpu_shuffle_")
                if self.shuffle_transport == "socket" and self._fetch_server is None:
                    from .fetch_server import ShuffleFetchServer

                    self._fetch_server = ShuffleFetchServer(self._shuffle_dir)
            return self._pool

    def run_iter(self, builder: LogicalPlanBuilder) -> Iterator[MicroPartition]:
        """Execute with the full observability lifecycle: subscriber events
        (QueryStart/Optimized/End) like the native runner, PLUS a QueryTrace
        that collects per-task stats, per-stage shuffle counters, and worker
        heartbeats from the pool — emitted to subscribers at query end and
        kept on `self.last_trace` for distributed EXPLAIN ANALYZE."""
        import time
        import uuid

        from ..execution.executor import execute_plan
        from ..observability import (QueryEnd, QueryOptimized, QueryStart,
                                     notify, subscribers_active)
        from ..observability.metrics import registry
        from ..observability.runtime_stats import (StatsCollector,
                                                   current_collector,
                                                   set_collector)
        from ..plan.physical import translate
        from .trace import QueryTrace

        pool = self._ensure_pool()
        # discard beats buffered in the worker pipes since the LAST drain
        # (the idle gap between queries): the end-of-query window filter
        # below judges by driver receive time, and these would all be
        # stamped inside THIS query's window at the first poll. Queued
        # worker-DEATH events survive this discard (preserve_deaths) — they
        # are one-shot and the dashboard's dead-worker latch needs them
        pool.drain_heartbeats(preserve_deaths=True)
        observed = subscribers_active()
        prev = current_collector()
        # trace when anyone is watching: attached subscribers OR an ambient
        # collector (explain_analyze / DataFrame.metrics). Otherwise tasks run
        # with collect_stats=False — the distributed zero-overhead path.
        traced = observed or prev is not None
        qid = uuid.uuid4().hex[:12] if traced else ""
        t_start = time.perf_counter()
        t_wall0 = time.time()
        reg_before = registry().snapshot() if traced else {}
        if observed:
            notify("on_query_start", QueryStart(qid, builder.plan.display()))
        t0 = time.perf_counter()
        optimized = builder.optimize()
        # translate with the driver's own config: the driver-side remainder may
        # use the device; Device* nodes inside shipped subtrees SURVIVE
        # distribution (planner.py DeviceGroupedAgg two-phase split) — each
        # worker's executor picks device vs host from its own leased config
        phys = translate(optimized.plan)
        if observed:
            notify("on_query_optimized", QueryOptimized(
                qid, optimized.plan.display(), phys.display(),
                time.perf_counter() - t0))
        trace = QueryTrace(qid) if traced else None
        if trace is not None:
            # trace epoch = query start (pre-optimize), so the timeline's
            # t=0 is where the user's wall clock started, not post-planning
            trace.started_wall = t_wall0
        self.last_trace = trace
        endpoints = [self._fetch_server.endpoint] if self._fetch_server else None
        ctx = DistContext(pool=pool, shuffle_dir=self._shuffle_dir,
                          n_partitions=self.n_partitions,
                          fetch_endpoints=endpoints, trace=trace,
                          ckpt=self._make_checkpointer(phys))
        collector = prev if prev is not None \
            else (StatsCollector() if observed else None)
        # driver-side placement scope (worker-side decisions stay in each
        # worker's own process ledger): the driver remainder's device stages
        # still record, and explain_placement's ambient scope is inherited
        from ..observability import placement as _placement

        prev_scope = _placement.current_scope()
        pscope = prev_scope if prev_scope is not None \
            else (_placement.PlacementScope() if traced else None)
        rows = 0
        err = None
        try:
            set_collector(collector)
            _placement.set_scope(pscope)
            try:
                # localize EXECUTES distributed stages eagerly (shuffle + final
                # task waves run on the pool here, recording into the trace)
                plan = localize(ctx, phys)
                stream = execute_plan(plan)
            finally:
                set_collector(prev)
                _placement.set_scope(prev_scope)
            while True:
                set_collector(collector)
                _placement.set_scope(pscope)
                try:
                    part = next(stream)
                except StopIteration:
                    break
                finally:
                    set_collector(prev)
                    _placement.set_scope(prev_scope)
                rows += part.num_rows
                yield part
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            set_collector(prev)
            _placement.set_scope(prev_scope)
            # drain even when untraced so beats from idle periods or untraced
            # queries never pile up and get misattributed to a later query
            beats = pool.drain_heartbeats()
            if trace is not None:
                for hb in beats:
                    # only beats from THIS query's window, judged by the
                    # DRIVER-side receive stamp (0.5s slack): the worker's
                    # send clock may be skewed — that skew is exactly what
                    # clock_offsets() estimates from these beats, so a
                    # worker-clock filter would drop the skewed beats it
                    # needs (send-ts fallback for beats predating the stamp)
                    # dead=True synthetic beats are kept regardless of the
                    # window: a death during the idle gap before this query
                    # must still reach the dashboard's dead-worker latch
                    if hb.get("dead") or \
                            hb.get("recv_ts", hb.get("ts", 0.0)) >= t_wall0 - 0.5:
                        trace.add_heartbeat(hb)
                # a warm pool can run a whole query in less than one
                # heartbeat period, leaving NO beat inside the window; fall
                # back to each silent worker's latest known beat so the
                # dashboard reflects the full pool after fast queries too
                seen = {h.worker_id for h in trace.heartbeats}
                for wid, hb in pool.latest_heartbeats().items():
                    if wid not in seen:
                        trace.add_heartbeat(hb)
            if observed and trace is not None:
                for ts in list(trace.tasks):
                    notify("on_task_stats", qid, ts)
                for sh in trace.shuffle_stats():
                    notify("on_shuffle_stats", qid, sh)
                for hb in list(trace.heartbeats):
                    notify("on_worker_heartbeat", qid, hb)
                # the assembled QueryTrace itself (timeline profiler source):
                # the dashboard serves its Chrome trace as a download
                notify("on_query_trace", qid, trace)
            if observed:
                stats = collector.finish() if collector else []
                for s in stats:
                    notify("on_operator_stats", qid, s)
                notify("on_query_end", QueryEnd(
                    qid, rows, time.perf_counter() - t_start, err, stats,
                    metrics=registry().diff(reg_before),
                    placements=pscope.to_dicts() if pscope is not None else []))

    def _make_checkpointer(self, phys):
        """Stage-boundary checkpoint/resume, armed ONLY by
        DAFT_TPU_CHECKPOINT_DIR (the zero-overhead gate: with it unset the
        checkpoint subsystem is never imported and no checkpoint counters
        move). The CheckpointId is the plan's content fingerprint — a
        re-submission of the same plan over the same data resumes past every
        committed stage; a plan we cannot fingerprint by content (opaque scan
        tasks, UDF handles) safely runs uncheckpointed."""
        root = os.environ.get("DAFT_TPU_CHECKPOINT_DIR", "")
        if not root:
            return None
        try:
            from ..checkpoint.stages import StageCheckpointer, query_fingerprint

            fp = query_fingerprint(phys)
            if fp is None:
                return None
            # the partition count is part of the checkpoint identity: a
            # committed shuffle's p0..pN-1 files are only complete for the
            # SAME fan-out — resuming an 8-partition checkpoint on a
            # 4-partition runner would silently drop half the rows
            return StageCheckpointer(root, f"{fp}-p{self.n_partitions}")
        except Exception:  # lint: ignore[broad-except] -- checkpointing is advisory
            return None

    def shutdown(self) -> None:
        if self._fetch_server is not None:
            self._fetch_server.close()
            self._fetch_server = None
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None
        if self._owns_shuffle_dir and self._shuffle_dir and os.path.isdir(self._shuffle_dir):
            shutil.rmtree(self._shuffle_dir, ignore_errors=True)
            self._shuffle_dir = None

    def __del__(self):  # best-effort cleanup
        try:
            self.shutdown()
        except Exception:  # lint: ignore[broad-except] -- interpreter-teardown __del__: anything
            pass  # may already be torn down; raising here prints noise
