"""Distributed runner: the engine's Flotilla-equivalent entry point.

Reference parity: daft/runners/flotilla.py:573 (FlotillaRunner) +
src/daft-distributed/src/plan/runner.rs:173 (PlanRunner.run_plan). Usage:

    import daft_tpu
    from daft_tpu.distributed import DistributedRunner
    daft_tpu.runners.set_runner(DistributedRunner(num_workers=4))

Distributable subtrees (scans/maps/joins/grouped aggs/repartitions) execute as
sub-plan tasks across spawn-based worker processes with disk-backed Arrow-IPC
shuffles; the driver executes whatever remains (sorts, windows, writes) over
the gathered results.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Iterator, Optional

from ..core.micropartition import MicroPartition
from ..plan.builder import LogicalPlanBuilder
from ..runners.native import Runner
from .planner import DistContext, localize
from .worker import WorkerPool


class DistributedRunner(Runner):
    def __init__(self, num_workers: int = 4, n_partitions: Optional[int] = None,
                 slots_per_worker: int = 1, shuffle_dir: Optional[str] = None,
                 shuffle_transport: str = "local",
                 max_workers: Optional[int] = None,
                 device_workers: int = 0):
        """shuffle_transport: "local" (reduce tasks read the shared shuffle
        directory — single-host fast path) or "socket" (reduce tasks fetch
        partitions from the HMAC-authenticated ShuffleFetchServer, the
        multi-host topology; reference flight_server.rs)."""
        if shuffle_transport not in ("local", "socket"):
            raise ValueError(f"unknown shuffle transport {shuffle_transport!r}")
        self.num_workers = num_workers
        self.max_workers = max_workers
        self.device_workers = device_workers
        self.n_partitions = n_partitions or num_workers
        self.slots_per_worker = slots_per_worker
        self.shuffle_transport = shuffle_transport
        self._shuffle_dir = shuffle_dir
        self._owns_shuffle_dir = shuffle_dir is None
        self._pool: Optional[WorkerPool] = None
        self._fetch_server = None

    def _ensure_pool(self) -> WorkerPool:
        if self._pool is None:
            self._pool = WorkerPool(self.num_workers, self.slots_per_worker,
                                    max_workers=self.max_workers,
                                    device_workers=self.device_workers)
            if self._shuffle_dir is None:
                self._shuffle_dir = tempfile.mkdtemp(prefix="daft_tpu_shuffle_")
            if self.shuffle_transport == "socket" and self._fetch_server is None:
                from .fetch_server import ShuffleFetchServer

                self._fetch_server = ShuffleFetchServer(self._shuffle_dir)
        return self._pool

    def run_iter(self, builder: LogicalPlanBuilder) -> Iterator[MicroPartition]:
        from ..execution.executor import execute_plan
        from ..plan.physical import translate

        pool = self._ensure_pool()
        optimized = builder.optimize()
        # translate with the driver's own config: the driver-side remainder may
        # use the device; Device* nodes inside shipped subtrees SURVIVE
        # distribution (planner.py DeviceGroupedAgg two-phase split) — each
        # worker's executor picks device vs host from its own leased config
        phys = translate(optimized.plan)
        endpoints = [self._fetch_server.endpoint] if self._fetch_server else None
        ctx = DistContext(pool=pool, shuffle_dir=self._shuffle_dir,
                          n_partitions=self.n_partitions,
                          fetch_endpoints=endpoints)
        plan = localize(ctx, phys)
        yield from execute_plan(plan)

    def shutdown(self) -> None:
        if self._fetch_server is not None:
            self._fetch_server.close()
            self._fetch_server = None
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None
        if self._owns_shuffle_dir and self._shuffle_dir and os.path.isdir(self._shuffle_dir):
            shutil.rmtree(self._shuffle_dir, ignore_errors=True)
            self._shuffle_dir = None

    def __del__(self):  # best-effort cleanup
        try:
            self.shutdown()
        except Exception:
            pass
