"""Distributed execution engine: partitioned tasks over local worker processes.

The TPU-native counterpart of the reference's Flotilla layer
(/root/reference/src/daft-distributed): a scheduler assigns serialized
physical sub-plans ("SubPlanTask" — reference scheduling/task.rs:212
SwordfishTask) to workers; bulk data moves through a disk-backed Arrow-IPC
shuffle (reference src/daft-shuffles/src/shuffle_cache.rs). Control transport
is spawn-based worker processes over pipes (the reference uses Ray actors);
the scheduler/worker protocol is transport-agnostic so a gRPC/DCN multi-host
backend slots in behind the same WorkerHandle interface.
"""

from .runner import DistributedRunner
from .scheduler import Scheduler, Spread, WorkerAffinity, WorkerSnapshot
from .task import SubPlanTask, TaskResult

__all__ = [
    "DistributedRunner",
    "Scheduler",
    "Spread",
    "WorkerAffinity",
    "WorkerSnapshot",
    "SubPlanTask",
    "TaskResult",
]
