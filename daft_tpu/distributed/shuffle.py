"""Disk-backed Arrow-IPC shuffle cache.

Reference parity: src/daft-shuffles/src/shuffle_cache.rs:39 (InProgressShuffleCache
partitions each MicroPartition and writes Arrow IPC files per partition to local
disk) + server/flight_server.rs (partition fetch). Layout:

    {base}/{shuffle_id}/p{partition}/m{map_id}.arrow

Each map task appends one file per partition it produced rows for; a reduce
task for partition p streams every m*.arrow under p{p}/. On one host the
"fetch" is a file read; the multi-host path serves the same files over a
socket (see fetch_server) the way the reference serves them over Arrow Flight.
"""

from __future__ import annotations

import os
from typing import Iterator, List

import pyarrow as pa
import pyarrow.ipc as ipc

from ..core.micropartition import MicroPartition
from ..core.recordbatch import RecordBatch
from ..schema import Schema


def partition_dir(base: str, shuffle_id: str, partition_idx: int) -> str:
    return os.path.join(base, shuffle_id, f"p{partition_idx}")


class MapOutputWriter:
    """Streaming writer for one map task: per-partition IPC files opened lazily,
    appended batch-by-batch as the input streams through (the map task never
    materializes its whole output — matching the reference's incremental
    InProgressShuffleCache, shuffle_cache.rs:39)."""

    def __init__(self, base: str, shuffle_id: str, map_id: int, num_partitions: int):
        self.base = base
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.rows = [0] * num_partitions
        self._writers: dict = {}

    def append(self, partition_idx: int, batch: RecordBatch) -> None:
        if batch.num_rows == 0:
            return
        self.rows[partition_idx] += batch.num_rows
        table = batch.to_arrow()
        w = self._writers.get(partition_idx)
        if w is None:
            d = partition_dir(self.base, self.shuffle_id, partition_idx)
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"m{self.map_id}.arrow")
            w = ipc.RecordBatchFileWriter(path, table.schema)
            self._writers[partition_idx] = w
        w.write_table(table)

    def close(self) -> List[int]:
        for w in self._writers.values():
            w.close()
        self._writers.clear()
        return self.rows


def write_map_output(base: str, shuffle_id: str, map_id: int,
                     partitioned: List[List[RecordBatch]]) -> List[int]:
    """Persist one map task's per-partition batches; returns rows per partition."""
    out = MapOutputWriter(base, shuffle_id, map_id, len(partitioned))
    for p, batches in enumerate(partitioned):
        for b in batches:
            out.append(p, b)
    return out.close()


def read_partition(base: str, shuffle_id: str, partition_idx: int,
                   schema: Schema) -> Iterator[MicroPartition]:
    """Stream every map's output for one shuffle partition."""
    d = partition_dir(base, shuffle_id, partition_idx)
    if not os.path.isdir(d):
        return
    for name in sorted(os.listdir(d)):
        if not name.endswith(".arrow"):
            continue
        with ipc.RecordBatchFileReader(os.path.join(d, name)) as r:
            table = r.read_all()
        batch = RecordBatch.from_arrow(table).cast_to_schema(schema)
        yield MicroPartition(schema, [batch])


def cleanup(base: str, shuffle_id: str) -> None:
    import shutil

    shutil.rmtree(os.path.join(base, shuffle_id), ignore_errors=True)
