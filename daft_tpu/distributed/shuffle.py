"""Disk-backed Arrow-IPC shuffle cache.

Reference parity: src/daft-shuffles/src/shuffle_cache.rs:39 (InProgressShuffleCache
partitions each MicroPartition and writes compressed Arrow IPC files per
partition to local disk) + server/flight_server.rs (partition fetch). Layout:

    {base}/{shuffle_id}/p{partition}/m{map_id}.arrow

Each map task appends one file per partition it produced rows for; a reduce
task for partition p streams every m*.arrow under p{p}/. On one host the
"fetch" is a file read; the multi-host path serves the same files over a
socket (see fetch_server) the way the reference serves them over Arrow Flight.

Wire format: Arrow IPC *stream* files with per-message body compression
(ExecutionConfig.shuffle_compression: none|lz4|zstd, default lz4 — the
reference's flight payloads are compressed the same way). Readers auto-detect
both the codec (from the IPC message headers) and the container (stream vs
legacy file format, from the ARROW1 magic), and decode batch-by-batch so
reduce-side memory is bounded by a few batches, never a whole map file.

Two byte measures flow through the counters so the compression ratio is
attributable end to end: `shuffle_logical_bytes` (uncompressed Arrow buffer
bytes of what was written) and `shuffle_wire_bytes` (the bytes that actually
hit disk/the socket).
"""

from __future__ import annotations

import io
import os
import threading
import time
from typing import Iterator, List, Optional

import pyarrow.ipc as ipc

from ..core.micropartition import MicroPartition
from ..core.recordbatch import RecordBatch
from ..observability.metrics import registry
from ..schema import Schema
from . import faults

_ARROW_FILE_MAGIC = b"ARROW1"


class ShuffleDataLost(RuntimeError):
    """A reduce task found shuffle map outputs MISSING that the map stage is
    known to have produced (`ShuffleRead.expected_maps`): the worker/host that
    wrote them is gone along with its files. Carries the precise lost map ids
    so the driver can re-execute exactly those map tasks from lineage
    (planner._regenerate_maps) instead of failing — or hanging on — the query.
    """

    def __init__(self, shuffle_id: str, map_ids, message: Optional[str] = None):
        self.shuffle_id = shuffle_id
        self.map_ids = tuple(map_ids)
        super().__init__(message or (
            f"shuffle {shuffle_id}: map outputs {sorted(self.map_ids)} "
            f"missing (worker storage lost)"))


class ShufflePeerUnreachable(RuntimeError):
    """A fetch peer refused/reset connections past the transient-retry budget
    (DAFT_TPU_FETCH_RETRIES): the host serving part of this shuffle is dead.
    Which map outputs it held is unknown to the client, so the driver's
    recovery path regenerates every map of the shuffle (bounded rounds)."""

    def __init__(self, shuffle_id: str, message: Optional[str] = None):
        self.shuffle_id = shuffle_id
        super().__init__(message or f"shuffle {shuffle_id}: peer unreachable")


def partition_dir(base: str, shuffle_id: str, partition_idx: int) -> str:
    return os.path.join(base, shuffle_id, f"p{partition_idx}")


class ShuffleRecorder:
    """Accumulates one task's shuffle volume: bytes/rows/partitions written by
    map tasks, bytes/rows/latency fetched by reduce tasks. Installed by the
    worker loop around each task (workers execute one task at a time, but the
    executor may drive shuffle reads from stage/pool threads — hence the lock).
    The snapshot ships back with the TaskResult for per-stage aggregation.

    Fetch timing is recorded on two axes because fetches overlap (pipelined
    requests, multi-peer fan-in): `fetch_seconds` is the CUMULATIVE in-flight
    time summed over requests (it over-counts wall time by design once
    requests run concurrently), `fetch_wall_seconds` is the union transfer
    window. Their difference is the transfer overlap the pipelined transport
    bought.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.bytes_written = 0          # logical (uncompressed Arrow) bytes
        self.wire_bytes_written = 0     # bytes that hit disk/the socket
        self.rows_written = 0
        self.partitions_written: set = set()
        self.bytes_fetched = 0          # wire bytes received
        self.rows_fetched = 0
        self.fetch_seconds = 0.0        # cumulative per-request in-flight time
        self.fetch_wall_seconds = 0.0   # union transfer window
        self.overlap_seconds = 0.0      # cumulative - window, per fetch call
        self.fetch_requests = 0
        self.fetch_fanin = 0            # max concurrent fetch connections

    def record_write(self, shuffle_id: str, partition_idx: int,
                     rows: int, nbytes: int) -> None:
        with self._lock:
            self.bytes_written += nbytes
            self.rows_written += rows
            self.partitions_written.add((shuffle_id, partition_idx))

    def record_write_wire(self, nbytes: int) -> None:
        with self._lock:
            self.wire_bytes_written += nbytes

    def record_fetch(self, rows: int, nbytes: int, seconds: float,
                     requests: int = 1) -> None:
        with self._lock:
            self.bytes_fetched += nbytes
            self.rows_fetched += rows
            self.fetch_seconds += seconds
            self.fetch_requests += requests

    def record_fetch_wall(self, wall_seconds: float, fanin: int,
                          overlap_seconds: float) -> None:
        with self._lock:
            self.fetch_wall_seconds += wall_seconds
            self.overlap_seconds += overlap_seconds
            self.fetch_fanin = max(self.fetch_fanin, fanin)

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "bytes_written": self.bytes_written,
                "wire_bytes_written": self.wire_bytes_written,
                "rows_written": self.rows_written,
                "partitions_written": len(self.partitions_written),
                "bytes_fetched": self.bytes_fetched,
                "rows_fetched": self.rows_fetched,
                "fetch_seconds": self.fetch_seconds,
                "fetch_wall_seconds": self.fetch_wall_seconds,
                "overlap_seconds": self.overlap_seconds,
                "fetch_requests": self.fetch_requests,
                "fetch_fanin": self.fetch_fanin,
            }


# process-global active recorder: workers run one task at a time, so a single
# slot suffices; None (the default everywhere else) costs one attribute read
_ACTIVE_RECORDER: Optional[ShuffleRecorder] = None


def set_recorder(r: Optional[ShuffleRecorder]) -> None:
    global _ACTIVE_RECORDER
    _ACTIVE_RECORDER = r


def current_recorder() -> Optional[ShuffleRecorder]:
    return _ACTIVE_RECORDER


# Map-output lineage sink: the worker loop installs a fresh list per task
# (ALWAYS, independent of stats collection — this is correctness-bearing, not
# telemetry); MapOutputWriter.close() records one entry per map task
# ({shuffle_id, map_id, rows-per-partition, published paths}) and the entry
# ships back in TaskResult.map_outputs. The driver derives each reduce
# partition's expected_maps from these rows, which is what lets a reduce
# DETECT silently-missing files instead of producing wrong results.
_ACTIVE_MAP_OUTPUTS: Optional[list] = None


def set_map_outputs(sink: Optional[list]) -> None:
    global _ACTIVE_MAP_OUTPUTS
    _ACTIVE_MAP_OUTPUTS = sink


def _note_map_output(entry: dict) -> None:
    sink = _ACTIVE_MAP_OUTPUTS
    if sink is not None:
        sink.append(entry)


def _note_write(shuffle_id: str, partition_idx: int, rows: int, nbytes: int) -> None:
    registry().inc("shuffle_bytes_written", nbytes)
    registry().inc("shuffle_logical_bytes", nbytes)
    registry().inc("shuffle_rows_written", rows)
    r = _ACTIVE_RECORDER
    if r is not None:
        r.record_write(shuffle_id, partition_idx, rows, nbytes)


def _note_write_wire(nbytes: int) -> None:
    registry().inc("shuffle_wire_bytes", nbytes)
    r = _ACTIVE_RECORDER
    if r is not None:
        r.record_write_wire(nbytes)


def _note_fetch(rows: int, nbytes: int, seconds: float) -> None:
    registry().inc("shuffle_bytes_fetched", nbytes)
    registry().inc("shuffle_rows_fetched", rows)
    registry().inc("shuffle_fetch_seconds", seconds)
    r = _ACTIVE_RECORDER
    if r is not None:
        r.record_fetch(rows, nbytes, seconds)


def _note_fetch_wall(wall_seconds: float, fanin: int,
                     overlap_seconds: float) -> None:
    registry().inc("shuffle_fetch_wall_seconds", wall_seconds)
    if overlap_seconds > 0:
        registry().inc("shuffle_overlap_seconds", overlap_seconds)
    r = _ACTIVE_RECORDER
    if r is not None:
        r.record_fetch_wall(wall_seconds, fanin, overlap_seconds)


class _ChainReader(io.RawIOBase):
    """Readable that serves a peeked prefix before delegating to the source
    (iter_ipc_batches sniffs the container magic without requiring seek)."""

    def __init__(self, head: bytes, rest):
        self._head = head
        self._rest = rest

    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        if self._head:
            n = min(len(b), len(self._head))
            b[:n] = self._head[:n]
            self._head = self._head[n:]
            return n
        data = self._rest.read(len(b))
        if not data:
            return 0
        b[: len(data)] = data
        return len(data)


def iter_ipc_batches(source) -> Iterator:
    """Yield pyarrow RecordBatches from a readable binary file-like object,
    one at a time (never read_all — reduce-side memory stays bounded by a
    batch, and the first batch is decodable before the last byte arrives).

    Auto-detects the container: Arrow IPC *stream* format (what
    MapOutputWriter emits) is decoded incrementally; the legacy *file* format
    (pre-compression shuffle dirs, or external tooling) is materialized and
    read batch-by-batch. Per-message compression (lz4/zstd) is handled by the
    IPC reader transparently — the codec travels in the message headers.
    """
    head = source.read(len(_ARROW_FILE_MAGIC))
    if head.startswith(_ARROW_FILE_MAGIC):
        # legacy file format needs random access (footer at the end)
        data = head + source.read()
        with ipc.RecordBatchFileReader(io.BytesIO(data)) as r:
            for i in range(r.num_record_batches):
                yield r.get_batch(i)
        return
    with ipc.open_stream(io.BufferedReader(_ChainReader(head, source))) as r:
        for batch in r:
            yield batch


class MapOutputWriter:
    """Streaming writer for one map task: per-partition IPC files opened lazily,
    appended batch-by-batch as the input streams through (the map task never
    materializes its whole output — matching the reference's incremental
    InProgressShuffleCache, shuffle_cache.rs:39). Files are IPC *stream*
    format with body compression from ExecutionConfig.shuffle_compression
    unless overridden per-writer."""

    def __init__(self, base: str, shuffle_id: str, map_id: int,
                 num_partitions: int, compression: Optional[str] = None):
        if compression is None:
            from ..config import execution_config

            compression = execution_config().shuffle_compression
        self.base = base
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.compression = compression
        self.rows = [0] * num_partitions
        self._opts = ipc.IpcWriteOptions(
            compression=None if compression == "none" else compression)
        self._writers: dict = {}
        self._paths: dict = {}

    def append(self, partition_idx: int, batch: RecordBatch) -> None:
        if batch.num_rows == 0:
            return
        if faults.ENABLED and not self._writers:
            # stage filter resolves via faults.set_stage (worker loop)
            faults.maybe_trip("shuffle_map")
        self.rows[partition_idx] += batch.num_rows
        table = batch.to_arrow()
        w = self._writers.get(partition_idx)
        if w is None:
            d = partition_dir(self.base, self.shuffle_id, partition_idx)
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"m{self.map_id}.arrow")
            # atomic publish: stream into a per-attempt temp name (readers
            # filter on the exact m<id>.arrow pattern, so it is invisible)
            # and os.replace() into place on close. Two attempts of the same
            # deterministic map task — a speculative duplicate racing the
            # original, or a retry racing a half-dead worker — then publish
            # identical content last-writer-wins instead of interleaving
            # writes into one corrupt file.
            import uuid as _uuid

            tmp = f"{path}.inprogress-{_uuid.uuid4().hex[:8]}"
            w = ipc.new_stream(tmp, table.schema, options=self._opts)
            self._writers[partition_idx] = w
            self._paths[partition_idx] = (tmp, path)
        w.write_table(table)
        _note_write(self.shuffle_id, partition_idx, batch.num_rows, table.nbytes)

    def close(self) -> List[int]:
        wire = 0
        published: List[str] = []
        for p, w in self._writers.items():
            w.close()
            tmp, path = self._paths[p]
            try:
                os.replace(tmp, path)
                wire += os.path.getsize(path)
                published.append(path)
            except OSError:
                pass
        self._writers.clear()
        self._paths.clear()
        if wire:
            _note_write_wire(wire)
        # lineage record — emitted even for an all-empty map output (the
        # driver must learn the map ran and produced nothing, so no reduce
        # partition waits for files that will never exist)
        _note_map_output({"shuffle_id": self.shuffle_id, "map_id": self.map_id,
                          "rows": list(self.rows), "paths": published})
        return self.rows


def write_map_output(base: str, shuffle_id: str, map_id: int,
                     partitioned: List[List[RecordBatch]],
                     compression: Optional[str] = None) -> List[int]:
    """Persist one map task's per-partition batches; returns rows per partition."""
    out = MapOutputWriter(base, shuffle_id, map_id, len(partitioned),
                          compression=compression)
    for p, batches in enumerate(partitioned):
        for b in batches:
            out.append(p, b)
    return out.close()


def check_expected_maps(shuffle_id: str, expected_maps, present) -> None:
    """Raise ShuffleDataLost naming exactly the map ids whose files are
    missing from `present` (an iterable of file names). The completeness
    gate that turns silent data loss — a dead worker's shuffle files gone —
    into a recoverable, attributable error."""
    if not expected_maps:
        return
    have = set(present)
    missing = [m for m in expected_maps if f"m{m}.arrow" not in have]
    if missing:
        raise ShuffleDataLost(shuffle_id, missing)


def read_partition(base: str, shuffle_id: str, partition_idx: int,
                   schema: Schema, expected_maps=None) -> Iterator[MicroPartition]:
    """Stream every map's output for one shuffle partition, one IPC batch at a
    time (peak memory is a batch, not a map file). Fetch time excludes the
    consumer's processing between yields (segmented timing).

    `expected_maps` (ShuffleRead.expected_maps — the map ids the driver's
    lineage says wrote rows for this partition) arms the completeness check:
    a missing file raises ShuffleDataLost instead of silently yielding a
    partial reduce input. None/() preserves the legacy read-what-exists
    behavior (direct callers, pre-lineage shuffle dirs)."""
    d = partition_dir(base, shuffle_id, partition_idx)
    if expected_maps:
        present = os.listdir(d) if os.path.isdir(d) else ()
        check_expected_maps(shuffle_id, expected_maps, present)
    if not os.path.isdir(d):
        return
    # timeline profiling: one "shuffle.read" slice per partition (local
    # shared-dir transport), covering the whole consumption window
    from ..observability.runtime_stats import span_iter

    inner = _read_partition_inner(d, schema)
    from ..memory.manager import manager

    if manager().limit_bytes() > 0:
        # budgeted reduce: decode ahead on the spill IO pool so decompress
        # overlaps the consumer's reduce compute (depth-bounded, and gated
        # on the budget so unbudgeted queries never touch the pool)
        from ..config import execution_config
        from ..memory.spill import prefetch_iter

        cfg = execution_config()
        if cfg.spill_io_threads > 0 and cfg.spill_prefetch_batches > 0:
            inner = prefetch_iter(lambda: _read_partition_inner(d, schema),
                                  cfg.spill_prefetch_batches,
                                  cfg.spill_io_threads, counters=False)
    yield from span_iter("shuffle.read", "io", inner,
                         shuffle_id=shuffle_id, partition=partition_idx)


def _read_partition_inner(d: str, schema: Schema) -> Iterator[MicroPartition]:
    for name in sorted(os.listdir(d)):
        if not name.endswith(".arrow"):
            continue
        path = os.path.join(d, name)
        rows = 0
        spent = 0.0
        nbytes = 0
        with open(path, "rb") as f:
            t0 = time.perf_counter()
            try:
                for rb in iter_ipc_batches(f):
                    batch = RecordBatch.from_arrow(rb).cast_to_schema(schema)
                    rows += batch.num_rows
                    spent += time.perf_counter() - t0
                    yield MicroPartition(schema, [batch])
                    t0 = time.perf_counter()
                spent += time.perf_counter() - t0
                nbytes = os.path.getsize(path)
            except BaseException:
                # consumer closed the generator (or decode failed) mid-file:
                # account what was actually read off disk so far
                nbytes = f.tell()
                raise
            finally:
                if rows or nbytes:
                    _note_fetch(rows, nbytes, spent)


def cleanup(base: str, shuffle_id: str) -> None:
    import shutil

    shutil.rmtree(os.path.join(base, shuffle_id), ignore_errors=True)
