"""Disk-backed Arrow-IPC shuffle cache.

Reference parity: src/daft-shuffles/src/shuffle_cache.rs:39 (InProgressShuffleCache
partitions each MicroPartition and writes Arrow IPC files per partition to local
disk) + server/flight_server.rs (partition fetch). Layout:

    {base}/{shuffle_id}/p{partition}/m{map_id}.arrow

Each map task appends one file per partition it produced rows for; a reduce
task for partition p streams every m*.arrow under p{p}/. On one host the
"fetch" is a file read; the multi-host path serves the same files over a
socket (see fetch_server) the way the reference serves them over Arrow Flight.
"""

from __future__ import annotations

import os
import threading
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.ipc as ipc

from ..core.micropartition import MicroPartition
from ..core.recordbatch import RecordBatch
from ..observability.metrics import registry
from ..schema import Schema


def partition_dir(base: str, shuffle_id: str, partition_idx: int) -> str:
    return os.path.join(base, shuffle_id, f"p{partition_idx}")


class ShuffleRecorder:
    """Accumulates one task's shuffle volume: bytes/rows/partitions written by
    map tasks, bytes/rows/latency fetched by reduce tasks. Installed by the
    worker loop around each task (workers execute one task at a time, but the
    executor may drive shuffle reads from stage/pool threads — hence the lock).
    The snapshot ships back with the TaskResult for per-stage aggregation.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.bytes_written = 0
        self.rows_written = 0
        self.partitions_written: set = set()
        self.bytes_fetched = 0
        self.rows_fetched = 0
        self.fetch_seconds = 0.0
        self.fetch_requests = 0

    def record_write(self, shuffle_id: str, partition_idx: int,
                     rows: int, nbytes: int) -> None:
        with self._lock:
            self.bytes_written += nbytes
            self.rows_written += rows
            self.partitions_written.add((shuffle_id, partition_idx))

    def record_fetch(self, rows: int, nbytes: int, seconds: float,
                     requests: int = 1) -> None:
        with self._lock:
            self.bytes_fetched += nbytes
            self.rows_fetched += rows
            self.fetch_seconds += seconds
            self.fetch_requests += requests

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "bytes_written": self.bytes_written,
                "rows_written": self.rows_written,
                "partitions_written": len(self.partitions_written),
                "bytes_fetched": self.bytes_fetched,
                "rows_fetched": self.rows_fetched,
                "fetch_seconds": self.fetch_seconds,
                "fetch_requests": self.fetch_requests,
            }


# process-global active recorder: workers run one task at a time, so a single
# slot suffices; None (the default everywhere else) costs one attribute read
_ACTIVE_RECORDER: Optional[ShuffleRecorder] = None


def set_recorder(r: Optional[ShuffleRecorder]) -> None:
    global _ACTIVE_RECORDER
    _ACTIVE_RECORDER = r


def current_recorder() -> Optional[ShuffleRecorder]:
    return _ACTIVE_RECORDER


def _note_write(shuffle_id: str, partition_idx: int, rows: int, nbytes: int) -> None:
    registry().inc("shuffle_bytes_written", nbytes)
    registry().inc("shuffle_rows_written", rows)
    r = _ACTIVE_RECORDER
    if r is not None:
        r.record_write(shuffle_id, partition_idx, rows, nbytes)


def _note_fetch(rows: int, nbytes: int, seconds: float) -> None:
    registry().inc("shuffle_bytes_fetched", nbytes)
    registry().inc("shuffle_rows_fetched", rows)
    r = _ACTIVE_RECORDER
    if r is not None:
        r.record_fetch(rows, nbytes, seconds)


class MapOutputWriter:
    """Streaming writer for one map task: per-partition IPC files opened lazily,
    appended batch-by-batch as the input streams through (the map task never
    materializes its whole output — matching the reference's incremental
    InProgressShuffleCache, shuffle_cache.rs:39)."""

    def __init__(self, base: str, shuffle_id: str, map_id: int, num_partitions: int):
        self.base = base
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.rows = [0] * num_partitions
        self._writers: dict = {}

    def append(self, partition_idx: int, batch: RecordBatch) -> None:
        if batch.num_rows == 0:
            return
        self.rows[partition_idx] += batch.num_rows
        table = batch.to_arrow()
        w = self._writers.get(partition_idx)
        if w is None:
            d = partition_dir(self.base, self.shuffle_id, partition_idx)
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"m{self.map_id}.arrow")
            w = ipc.RecordBatchFileWriter(path, table.schema)
            self._writers[partition_idx] = w
        w.write_table(table)
        _note_write(self.shuffle_id, partition_idx, batch.num_rows, table.nbytes)

    def close(self) -> List[int]:
        for w in self._writers.values():
            w.close()
        self._writers.clear()
        return self.rows


def write_map_output(base: str, shuffle_id: str, map_id: int,
                     partitioned: List[List[RecordBatch]]) -> List[int]:
    """Persist one map task's per-partition batches; returns rows per partition."""
    out = MapOutputWriter(base, shuffle_id, map_id, len(partitioned))
    for p, batches in enumerate(partitioned):
        for b in batches:
            out.append(p, b)
    return out.close()


def read_partition(base: str, shuffle_id: str, partition_idx: int,
                   schema: Schema) -> Iterator[MicroPartition]:
    """Stream every map's output for one shuffle partition."""
    import time

    d = partition_dir(base, shuffle_id, partition_idx)
    if not os.path.isdir(d):
        return
    for name in sorted(os.listdir(d)):
        if not name.endswith(".arrow"):
            continue
        t0 = time.perf_counter()
        path = os.path.join(d, name)
        with ipc.RecordBatchFileReader(path) as r:
            table = r.read_all()
        batch = RecordBatch.from_arrow(table).cast_to_schema(schema)
        _note_fetch(batch.num_rows, os.path.getsize(path),
                    time.perf_counter() - t0)
        yield MicroPartition(schema, [batch])


def cleanup(base: str, shuffle_id: str) -> None:
    import shutil

    shutil.rmtree(os.path.join(base, shuffle_id), ignore_errors=True)
