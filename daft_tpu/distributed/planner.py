"""Distributed planner: partition a physical plan into shuffle-separated stages.

Reference parity: src/daft-distributed/src/pipeline_node/translate.rs:36
(logical plan -> DistributedPipelineNode DAG) + pipeline_node/join/translate_join.rs
(co-partitioning decisions). Model:

- ``distribute(ctx, node)`` returns N plan *fragments* (one per partition) plus
  the hash-partitioning property their outputs satisfy.
- Map ops (project/filter/...) compose into the fragment sub-plans.
- Exchange points (join/grouped-agg inputs not already co-partitioned, explicit
  repartitions) run eagerly as a stage of ShuffleWrite tasks on the worker
  pool; downstream fragments read via ShuffleRead.
- ``localize()`` replaces each maximal distributable subtree with an
  InMemoryScan of its distributed result; the driver executes the remainder
  (sort/window/writes/...) locally.

Two-phase grouped aggregation reuses plan/agg_split (the same partial/final
decomposition the local engine uses), so a distributed groupby is:
partial-agg fragments -> hash shuffle on keys -> final-agg fragments.
"""

from __future__ import annotations

import itertools
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..cancellation import raise_if_cancelled
from ..core.micropartition import MicroPartition
from ..core.recordbatch import RecordBatch
from ..expressions import ColumnRef
from ..expressions.expressions import Alias
from ..observability.metrics import registry
from ..plan import physical as pp
from ..utils.env import env_int
from .shuffle import ShuffleDataLost, ShufflePeerUnreachable
from .task import SubPlanTask


@dataclass
class DistContext:
    pool: object               # WorkerPool
    shuffle_dir: str
    n_partitions: int
    # fetch-server endpoints [(host, port, authkey_hex)]; when set, reduce
    # tasks read shuffle partitions over the socket tier, never the local dir
    fetch_endpoints: Optional[list] = None
    # QueryTrace (distributed/trace.py): when set, every stage's tasks are
    # stamped with the query's trace context and their runtime stats recorded
    trace: Optional[object] = None
    # StageCheckpointer (checkpoint/stages.py) — None unless
    # DAFT_TPU_CHECKPOINT_DIR is set AND the plan fingerprinted (the
    # zero-overhead gate lives in DistributedRunner)
    ckpt: Optional[object] = None
    _task_seq: itertools.count = None  # type: ignore[assignment]
    _stage_seq: itertools.count = None  # type: ignore[assignment]
    _run_tag: str = ""
    shuffle_ids: List[str] = None  # type: ignore[assignment]
    # shuffle lineage: shuffle_id -> {map_id: SubPlanTask}, retained for the
    # query's lifetime so lost map outputs can be re-executed from their
    # original plan blobs (the task -> plan_blob -> output partitions chain)
    lineage: Dict[str, dict] = None  # type: ignore[assignment]
    # bounded regeneration budget (DAFT_TPU_SHUFFLE_REGEN_ROUNDS, default 2):
    # each lost-data recovery consumes one round; exhaustion fails the query
    # cleanly instead of looping against a flapping cluster
    regen_rounds_left: int = 0
    # checkpoint keying state: subtree-scoped so stage keys are deterministic
    # regardless of how many earlier subtrees were resumed from checkpoints
    _subtree_seq: itertools.count = None  # type: ignore[assignment]
    ckpt_subtree: str = ""
    ckpt_shuffle_seq: int = 0

    def __post_init__(self):
        self._task_seq = itertools.count()
        self._stage_seq = itertools.count()
        self._subtree_seq = itertools.count()
        # unique per context: a reused pool must never confuse this run's task
        # ids with a previous query's (stale-result isolation)
        self._run_tag = uuid.uuid4().hex[:8]
        self.shuffle_ids = []
        self.lineage = {}
        self.regen_rounds_left = env_int("DAFT_TPU_SHUFFLE_REGEN_ROUNDS", 2,
                                         lo=0)

    def task_id(self, prefix: str) -> str:
        return f"{prefix}-{self._run_tag}-{next(self._task_seq)}"

    def stage_id(self, kind: str) -> str:
        return f"{kind}:{next(self._stage_seq)}"


@dataclass
class Partitioned:
    fragments: List[pp.PhysicalPlan]
    # hash-partition property: column names the fragments are co-partitioned on
    # (None = unknown/none). Only ever set for fragment lists of length
    # ctx.n_partitions produced by a shuffle (or preserved through map ops).
    partitioned_by: Optional[Tuple[str, ...]] = None


_MAP_NODES = (pp.Project, pp.PhysFilter, pp.UDFProject, pp.DeviceUdfProject,
              pp.PhysExplode, pp.PhysUnpivot, pp.PhysSample)
_SUPPORTED = _MAP_NODES + (pp.InMemoryScan, pp.TaskScan, pp.HashJoin,
                           pp.HashAggregate, pp.PhysRepartition, pp.Dedup,
                           pp.DeviceGroupedAgg)


def subtree_distributable(node: pp.PhysicalPlan) -> bool:
    for n in node.walk():
        if not isinstance(n, _SUPPORTED):
            return False
        if isinstance(n, pp.TaskScan) and n.post_limit is not None:
            return False
        if isinstance(n, pp.PhysRepartition) and n.scheme not in ("hash",):
            return False
        if isinstance(n, pp.HashJoin) and n.how == "cross":
            return False
    return True


def worth_distributing(node: pp.PhysicalPlan, min_rows: int = 0) -> bool:
    """Only ship subtrees containing an exchange-heavy op; pure scans/maps are
    cheaper executed in-process than serialized across workers.
    DeviceGroupedAgg counts: it IS a grouped aggregation (the device-lowered
    form), and omitting it silently kept every plain groupby on the driver."""
    return any(isinstance(n, (pp.HashJoin, pp.HashAggregate, pp.PhysRepartition,
                              pp.Dedup, pp.DeviceGroupedAgg))
               for n in node.walk())


def _fingerprint(ctx: DistContext, frag: pp.PhysicalPlan):
    """Residency fingerprint for one fragment — but ONLY once some worker
    actually holds planes (a non-empty heartbeat digest). On a cold pool every
    digest is empty and affinity can never hit, so skipping the fingerprint
    skips its content-hash pass over the fragment's input columns on the
    dispatch path; from the second query on, the hashes are computed (and
    memoized per Series) exactly when they can pay off."""
    from .affinity import plan_fingerprint

    try:
        workers = getattr(ctx.pool, "workers", {}).values()
        if not any(getattr(w, "last_digest", None) for w in workers):
            return ()
    except Exception:  # lint: ignore[broad-except] -- affinity fingerprint is advisory
        return ()
    return plan_fingerprint(frag)


def localize(ctx: DistContext, node: pp.PhysicalPlan) -> pp.PhysicalPlan:
    """Replace maximal distributable subtrees with their distributed results."""
    if subtree_distributable(node) and worth_distributing(node):
        parts = run_distributed(ctx, node)
        return pp.InMemoryScan(parts, node.schema)
    if isinstance(node, pp.PhysConcat):
        node.inputs = [localize(ctx, c) for c in node.inputs]
        return node
    if isinstance(node, (pp.HashJoin, pp.CrossJoin)):
        node.left = localize(ctx, node.left)
        node.right = localize(ctx, node.right)
        return node
    if hasattr(node, "input"):
        node.input = localize(ctx, node.input)
    return node


def _run_stage_recovering(ctx: DistContext, make_tasks, stage: str):
    """Run one task stage with lost-shuffle recovery: a reduce-side
    ShuffleDataLost (precise missing map ids) or ShufflePeerUnreachable
    (whole peer gone — every map of the shuffle is suspect) re-executes the
    lost map tasks from lineage on the surviving workers, then retries the
    stage with FRESH task ids (a stale in-flight result from the aborted
    attempt can never be mistaken for the retry's). Bounded by
    ctx.regen_rounds_left; exhaustion raises the final loss cleanly.

    `make_tasks` builds the stage's task list — called once per attempt so
    retries are new task objects, never mutated reruns. Returns
    (tasks, results)."""
    while True:
        raise_if_cancelled()
        tasks = make_tasks()
        try:
            return tasks, ctx.pool.run_tasks(tasks, stage_id=stage,
                                             trace=ctx.trace)
        except (ShuffleDataLost, ShufflePeerUnreachable) as e:
            if ctx.regen_rounds_left <= 0:
                raise
            ctx.regen_rounds_left -= 1
            lost = e.map_ids if isinstance(e, ShuffleDataLost) else None
            _regenerate_maps(ctx, e.shuffle_id, lost, cause=e)


def _regenerate_maps(ctx: DistContext, shuffle_id: str,
                     map_ids: Optional[Tuple[int, ...]], cause) -> None:
    """Re-execute lost map tasks (map_ids; None = all of the shuffle) from
    lineage on the surviving workers. The regenerated outputs publish under
    the same deterministic file names (atomic tmp+rename), so the retried
    reduce simply finds them."""
    lin = ctx.lineage.get(shuffle_id)
    if lin is None:
        raise RuntimeError(
            f"shuffle {shuffle_id} data lost and no lineage retained — "
            f"cannot regenerate") from cause
    originals = lin["tasks"]
    wanted = sorted(originals) if map_ids is None else sorted(map_ids)
    missing = [m for m in wanted if m not in originals]
    if missing:
        raise RuntimeError(
            f"shuffle {shuffle_id}: lost map ids {missing} unknown to "
            f"lineage — cannot regenerate") from cause
    stage = ctx.stage_id("regen")

    def make_tasks():
        return [
            SubPlanTask(task_id=ctx.task_id("regen"),
                        plan_blob=originals[m].plan_blob,
                        strategy=originals[m].strategy,
                        priority=originals[m].priority,
                        stage_id=stage,
                        rfingerprint=originals[m].rfingerprint)
            for m in wanted
        ]

    # the regen stage runs under the same recovery wrapper: its map tasks may
    # themselves read an EARLIER shuffle whose files were on the dead worker
    # (cascading lineage replay, still bounded by regen_rounds_left)
    _run_stage_recovering(ctx, make_tasks, stage)
    registry().inc("shuffle_maps_regenerated_total", len(wanted))
    if ctx.trace is not None:
        ctx.trace.note_recovery("maps_regenerated", len(wanted))


def run_distributed(ctx: DistContext, node: pp.PhysicalPlan) -> List[MicroPartition]:
    """Distribute a subtree and run its final fragments as a task stage.

    Shuffle intermediates for this subtree are deleted once the results are
    gathered (reference: cluster-wide shuffle dir cleanup on plan end,
    daft/runners/flotilla.py:70-106).

    With checkpointing armed (ctx.ckpt), a subtree whose result was committed
    by a previous run of the same plan fingerprint is restored wholesale —
    no stages run; otherwise the gathered result is committed at the
    boundary so a later re-submission can skip it.
    """
    from . import shuffle as shf

    subtree_key = None
    if ctx.ckpt is not None:
        idx = next(ctx._subtree_seq)
        ctx.ckpt_subtree = f"subtree-{idx}"
        ctx.ckpt_shuffle_seq = 0
        subtree_key = f"{ctx.ckpt_subtree}/result"
        restored = ctx.ckpt.restore_result(subtree_key, node.schema)
        if restored is not None:
            return restored
    try:
        dist = distribute(ctx, node)
        stage = ctx.stage_id("final")

        def make_tasks():
            return [SubPlanTask.from_plan(ctx.task_id("final"), frag,
                                          stage_id=stage,
                                          rfingerprint=_fingerprint(ctx, frag))
                    for frag in dist.fragments]

        tasks, results = _run_stage_recovering(ctx, make_tasks, stage)
        parts: List[MicroPartition] = []
        for t in tasks:  # preserve fragment order
            parts.extend(results[t.task_id].partitions)
        parts = parts or [MicroPartition.empty(node.schema)]
        if subtree_key is not None:
            ctx.ckpt.commit_result(subtree_key, parts)
        return parts
    finally:
        for sid in ctx.shuffle_ids:
            shf.cleanup(ctx.shuffle_dir, sid)
            ctx.lineage.pop(sid, None)
        ctx.shuffle_ids.clear()


def distribute(ctx: DistContext, node: pp.PhysicalPlan) -> Partitioned:
    N = ctx.n_partitions

    if isinstance(node, pp.InMemoryScan):
        groups = _split_partitions(node.partitions, N, node.schema)
        return Partitioned([pp.InMemoryScan(g, node.schema) for g in groups])

    if isinstance(node, pp.TaskScan):
        if len(node.tasks) <= 1:
            return Partitioned([node])
        groups = [node.tasks[i::N] for i in range(min(N, len(node.tasks)))]
        # preserve the concrete scan class: a StreamingScan fragment keeps
        # streaming (host-ledger pacing, scan counters) on its worker
        return Partitioned([
            type(node)(g, node.schema, node.post_filter, None) for g in groups if g
        ])

    if isinstance(node, _MAP_NODES):
        child = distribute(ctx, node.input)
        frags = []
        for f in child.fragments:
            clone = _clone_unary(node, f)
            frags.append(clone)
        keep = child.partitioned_by
        if keep is not None and not set(keep).issubset(set(node.schema.column_names())):
            keep = None  # partition keys projected away
        return Partitioned(frags, keep)

    if isinstance(node, pp.PhysRepartition):
        child = distribute(ctx, node.input)
        keys = _key_names(node.by)
        reads = _shuffle(ctx, child.fragments, node.by, node.schema)
        return Partitioned(reads, keys)

    if isinstance(node, pp.Dedup):
        # co-partition on the dedup keys, then dedup each partition independently
        child = distribute(ctx, node.input)
        from ..expressions import col as _col

        on = node.on or [_col(c) for c in node.input.schema.column_names()]
        keys = _key_names(on)
        if child.partitioned_by is None or child.partitioned_by != keys:
            reads = _shuffle(ctx, child.fragments, on, node.input.schema)
        else:
            reads = child.fragments
        return Partitioned([pp.Dedup(f, node.on, node.schema) for f in reads], keys)

    if isinstance(node, pp.HashJoin):
        left = distribute(ctx, node.left)
        # broadcast join: a build side small enough to replicate skips BOTH
        # shuffles — every left fragment joins against the full right sub-plan
        # (reference: pipeline_node/join broadcast variant + the 10MiB
        # broadcast_join_size_bytes default)
        from ..config import execution_config

        r_bytes = _phys_bytes_estimate(node.right)
        if (node.how in ("inner", "left", "semi", "anti")
                and r_bytes is not None
                and r_bytes <= execution_config().broadcast_join_size_bytes):
            frags = [
                pp.HashJoin(lf, node.right, node.left_on, node.right_on, node.how,
                            node.merged_keys, node.right_rename, node.schema,
                            node.null_equals_null)
                for lf in left.fragments
            ]
            keep = left.partitioned_by
            if keep is not None and not set(keep).issubset(set(node.schema.column_names())):
                keep = None
            return Partitioned(frags, keep)
        right = distribute(ctx, node.right)
        lkeys = _key_names(node.left_on)
        rkeys = _key_names(node.right_on)
        if left.partitioned_by is None or left.partitioned_by != lkeys:
            lfrags = _shuffle(ctx, left.fragments, node.left_on, node.left.schema)
        else:
            lfrags = left.fragments
        if right.partitioned_by is None or right.partitioned_by != rkeys:
            rfrags = _shuffle(ctx, right.fragments, node.right_on, node.right.schema)
        else:
            rfrags = right.fragments
        frags = [
            pp.HashJoin(lf, rf, node.left_on, node.right_on, node.how,
                        node.merged_keys, node.right_rename, node.schema,
                        node.null_equals_null)
            for lf, rf in zip(lfrags, rfrags)
        ]
        out_keys = lkeys if lkeys and set(lkeys).issubset(set(node.schema.column_names())) else None
        return Partitioned(frags, out_keys)

    if isinstance(node, pp.DeviceGroupedAgg):
        # Workers KEEP the device stage (VERDICT r4 next #5): each worker's
        # executor decides device-vs-host at runtime from ITS config — the
        # pool grants DAFT_TPU_DEVICE to `device_workers` workers (a device
        # lease; the rest run the identical host fallback). The partial phase
        # of the two-phase split stays a DeviceGroupedAgg when the split aggs
        # still qualify for the device stage.
        from ..ops.grouped_stage import try_build_grouped_agg_stage

        def device_frag(f, groupby, aggs, schema):
            if try_build_grouped_agg_stage(f.schema, node.predicate,
                                           groupby, aggs) is not None:
                return pp.DeviceGroupedAgg(f, node.predicate, groupby, aggs,
                                           schema)
            inner = f
            if node.predicate is not None:
                inner = pp.PhysFilter(inner, node.predicate, inner.schema)
            return pp.HashAggregate(inner, groupby, aggs, schema)

        def raw_frag(f):
            if node.predicate is not None:
                return pp.PhysFilter(f, node.predicate, f.schema)
            return f

        return _two_phase_agg(ctx, node, device_frag, raw_frag)

    if isinstance(node, pp.HashAggregate):
        return _two_phase_agg(
            ctx, node,
            lambda f, groupby, aggs, schema: pp.HashAggregate(
                f, groupby, aggs, schema),
            lambda f: f)

    raise NotImplementedError(f"distribute: unhandled node {type(node).__name__}")


def _two_phase_agg(ctx: DistContext, node, make_leaf, raw_frag) -> Partitioned:
    """Shared grouped-aggregation distribution (HashAggregate and
    DeviceGroupedAgg differ only in the leaf-agg constructor):
    co-partitioned -> aggregate in place; splittable -> partial per fragment,
    shuffle on keys, final combine; unsplittable -> shuffle raw rows."""
    from ..expressions import col as _col
    from ..plan.agg_split import split_aggs

    child = distribute(ctx, node.input)
    keys = _key_names(node.groupby)
    if child.partitioned_by is not None and child.partitioned_by == keys:
        frags = [make_leaf(f, node.groupby, node.aggregations, node.schema)
                 for f in child.fragments]
        return Partitioned(frags, keys)
    split = split_aggs(node.aggregations)
    if split is not None:
        partial_schema = _agg_schema(node.input.schema, node.groupby, split.partial)
        partials = [make_leaf(f, node.groupby, split.partial, partial_schema)
                    for f in child.fragments]
        key_cols = [_col(e.name()) for e in node.groupby]
        reads = _shuffle(ctx, partials, key_cols, partial_schema)
        frags = []
        for r in reads:
            final = pp.HashAggregate(
                r, key_cols, split.final,
                _agg_schema(partial_schema, key_cols, split.final))
            frags.append(pp.Project(final, key_cols + split.projection,
                                    node.schema))
        return Partitioned(frags, keys)
    # unsplittable aggs (e.g. count_distinct): shuffle raw rows by key
    reads = _shuffle(ctx, [raw_frag(f) for f in child.fragments],
                     node.groupby, node.input.schema)
    frags = [pp.HashAggregate(r, node.groupby, node.aggregations, node.schema)
             for r in reads]
    return Partitioned(frags, keys)


def _shuffle(ctx: DistContext, fragments: List[pp.PhysicalPlan], by,
             schema) -> List[pp.PhysicalPlan]:
    """Run a shuffle stage: wrap each fragment in ShuffleWrite, execute on the
    pool, return per-partition ShuffleRead fragments.

    Fault-tolerance bookkeeping: the map tasks are registered in
    ctx.lineage[sid] BEFORE the stage runs (regeneration source), and each
    reduce partition's ShuffleRead carries the expected map ids derived from
    the map results' rows-per-partition — the completeness contract that
    turns a dead worker's missing files into a ShuffleDataLost the recovery
    loop can act on. With checkpointing armed, a committed stage restores
    its files instead of re-running, and a fresh run commits at the boundary.
    """
    ckpt_key = None
    if ctx.ckpt is not None:
        ckpt_key = f"{ctx.ckpt_subtree}/shuffle-{ctx.ckpt_shuffle_seq}"
        ctx.ckpt_shuffle_seq += 1
        restored = ctx.ckpt.restore_shuffle(ckpt_key, ctx.shuffle_dir)
        if restored is not None:
            rsid, rexpected = restored
            ctx.shuffle_ids.append(rsid)
            return _shuffle_reads(ctx, rsid, schema, rexpected)
    sid = uuid.uuid4().hex[:12]
    ctx.shuffle_ids.append(sid)
    stage = ctx.stage_id("shuffle")

    def make_tasks():
        tasks = [
            SubPlanTask.from_plan(
                ctx.task_id("shuffle"),
                pp.ShuffleWrite(frag, sid, map_id=i,
                                num_partitions=ctx.n_partitions,
                                by=list(by), shuffle_dir=ctx.shuffle_dir,
                                schema=schema),
                stage_id=stage,
                # residency fingerprint of the map fragment (the device planes
                # its partial-agg stage would probe): repeat shuffles of a
                # resident table stick to the workers already holding them
                rfingerprint=_fingerprint(ctx, frag))
            for i, frag in enumerate(fragments)
        ]
        # lineage registered pre-run: keyed by map id so a retried attempt
        # (fresh task ids) overwrites in place
        ctx.lineage[sid] = {"stage": stage,
                            "tasks": {i: t for i, t in enumerate(tasks)}}
        return tasks

    tasks, results = _run_stage_recovering(ctx, make_tasks, stage)
    # derive per-partition expected maps from the lineage records the map
    # tasks shipped back (rows written per partition — a map that wrote no
    # rows for partition p legitimately has no file there)
    rows_by_map: Dict[int, List[int]] = {}
    for t in tasks:
        res = results[t.task_id]
        for mo in res.map_outputs:
            if mo.get("shuffle_id") == sid:
                rows_by_map[int(mo["map_id"])] = list(mo.get("rows", ()))
    expected = {
        p: tuple(sorted(m for m, rows in rows_by_map.items()
                        if p < len(rows) and rows[p] > 0))
        for p in range(ctx.n_partitions)
    }
    if ckpt_key is not None:
        ctx.ckpt.commit_shuffle(ckpt_key, ctx.shuffle_dir, sid, expected)
    return _shuffle_reads(ctx, sid, schema, expected)


def _shuffle_reads(ctx: DistContext, sid: str, schema,
                   expected: Dict[int, tuple]) -> List[pp.PhysicalPlan]:
    """The reduce-side fragments of a shuffle — ONE construction site for
    both the fresh and checkpoint-restored paths, so transport selection and
    the expected-maps completeness contract can never drift between them."""
    return [pp.ShuffleRead(sid, p,
                           "" if ctx.fetch_endpoints else ctx.shuffle_dir,
                           schema, ctx.fetch_endpoints,
                           expected_maps=expected.get(p))
            for p in range(ctx.n_partitions)]


def _phys_bytes_estimate(node: pp.PhysicalPlan) -> Optional[int]:
    """Upper-bound byte estimate for a physical subtree (broadcast decisions).
    Exact for in-memory sources; filters/projects pass through (upper bound);
    unknown sources return None (never broadcast blindly)."""
    if isinstance(node, pp.InMemoryScan):
        total = 0
        for p in node.partitions:
            for b in p.batches:
                total += b.size_bytes()
        return total
    if isinstance(node, pp.TaskScan):
        sizes = [t.size_bytes for t in node.tasks]
        if any(s is None for s in sizes):
            return None
        return int(sum(sizes))
    if isinstance(node, (pp.Project, pp.PhysFilter, pp.PhysLimit, pp.PhysSample)):
        return _phys_bytes_estimate(node.input)
    return None


def _key_names(exprs) -> Optional[Tuple[str, ...]]:
    names = []
    for e in exprs:
        node = e.child if isinstance(e, Alias) else e
        if not isinstance(node, ColumnRef):
            return None
        names.append(e.name())
    return tuple(names)


def _clone_unary(node, new_input):
    import copy

    clone = copy.copy(node)
    clone.input = new_input
    return clone


def _agg_schema(in_schema, groupby, aggs):
    from ..schema import Schema

    fields = [e.to_field(in_schema) for e in list(groupby) + list(aggs)]
    return Schema(fields)


def _split_partitions(partitions, n: int, schema) -> List[List[MicroPartition]]:
    """Round-robin micropartitions into n groups; a single big partition is
    sliced by rows so every worker gets real work."""
    parts = [p for p in partitions if p.num_rows > 0]
    if not parts:
        return [[MicroPartition.empty(schema)]]
    if len(parts) < n:
        batches = [b for p in parts for b in p.batches if b.num_rows > 0]
        total = sum(b.num_rows for b in batches)
        if total == 0:
            return [[MicroPartition.empty(schema)]]
        big = RecordBatch.concat(batches) if len(batches) > 1 else batches[0]
        step = (total + n - 1) // n
        groups = []
        for s in range(0, total, step):
            groups.append([MicroPartition(schema, [big.slice(s, min(s + step, total))])])
        return groups
    return [parts[i::n] for i in range(n)]
