"""Shuffle fetch server: partition files served over authenticated TCP.

Reference parity: src/daft-shuffles/src/server/flight_server.rs:72 (Arrow
Flight `do_get` streams one shuffle partition's files) + client/fetch.rs
fan-in. Here the transport is a multiprocessing.connection TCP listener —
the same HMAC challenge/response machinery the worker tier already uses —
serving the Arrow-IPC files written by MapOutputWriter (shuffle.py).

Topology: every host that runs map tasks starts one ShuffleFetchServer over
its local shuffle directory; reduce tasks fetch each partition from EVERY
endpoint and merge (map outputs for one partition are spread across hosts).
On a single host there is one endpoint, but the fan-in path is identical.

Protocol (pickle frames over the authenticated connection):
    -> ("list",  shuffle_id, partition_idx)          <- ("files", [name, ...])
    -> ("fetch", shuffle_id, partition_idx, name)    <- ("file", bytes)
    -> ("bye",)                                       closes the connection
"""

from __future__ import annotations

import os
import re
import secrets
import threading
from multiprocessing.connection import Client, Listener
from typing import Iterator, List, Optional, Tuple

import pyarrow as pa
import pyarrow.ipc as ipc

from ..core.micropartition import MicroPartition
from ..core.recordbatch import RecordBatch
from ..schema import Schema
from .shuffle import partition_dir

_SAFE_ID = re.compile(r"^[A-Za-z0-9_\-]+$")
_SAFE_FILE = re.compile(r"^m\d+\.arrow$")

Endpoint = Tuple[str, int, str]  # (host, port, authkey_hex)


class ShuffleFetchServer:
    """Serves one host's shuffle directory. Thread-per-connection; all state
    is the immutable base path, so concurrent fetches need no locks."""

    def __init__(self, base: str, host: str = "127.0.0.1", port: int = 0,
                 authkey: Optional[bytes] = None):
        self.base = base
        self.authkey = authkey if authkey is not None else secrets.token_bytes(32)
        self._listener = Listener((host, port), family="AF_INET", authkey=self.authkey)
        self._closed = False
        self._threads: List[threading.Thread] = []
        # served-request counters (reference: flight_server metrics); mirrored
        # into the metrics registry so EXPLAIN ANALYZE / bench can attribute
        # transport traffic
        self._stats_lock = threading.Lock()
        self.requests = 0
        self.bytes_served = 0
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="daft-shuffle-fetch")
        t.start()
        self._threads.append(t)

    def _note_request(self, nbytes: int = 0) -> None:
        from ..observability.metrics import registry

        with self._stats_lock:
            self.requests += 1
            self.bytes_served += nbytes
        registry().inc("shuffle_fetch_server_requests")
        if nbytes:
            registry().inc("shuffle_fetch_server_bytes", nbytes)

    def stats(self) -> dict:
        with self._stats_lock:
            return {"requests": self.requests, "bytes_served": self.bytes_served}

    @property
    def endpoint(self) -> Endpoint:
        host, port = self._listener.address
        return (host, port, self.authkey.hex())

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError, Exception):  # noqa: BLE001 — closed or bad auth
                if self._closed:
                    return
                continue
            threading.Thread(target=self._serve, args=(conn,), daemon=True,
                             name="daft-shuffle-conn").start()

    def _serve(self, conn) -> None:
        try:
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                if not msg or msg[0] == "bye":
                    return
                try:
                    if msg[0] == "list":
                        _kind, sid, pidx = msg
                        self._note_request()
                        conn.send(("files", self._list(sid, int(pidx))))
                    elif msg[0] == "fetch":
                        _kind, sid, pidx, name = msg
                        data = self._read(sid, int(pidx), name)
                        self._note_request(len(data))
                        conn.send(("file", data))
                    else:
                        conn.send(("error", f"unknown request {msg[0]!r}"))
                except Exception as e:  # noqa: BLE001 — refuse the request, keep serving
                    conn.send(("error", f"{type(e).__name__}: {e}"))
        finally:
            conn.close()

    def _pdir(self, shuffle_id: str, partition_idx: int) -> str:
        if not _SAFE_ID.match(shuffle_id):
            raise ValueError(f"bad shuffle id {shuffle_id!r}")
        return partition_dir(self.base, shuffle_id, partition_idx)

    def _list(self, shuffle_id: str, partition_idx: int) -> List[str]:
        d = self._pdir(shuffle_id, partition_idx)
        if not os.path.isdir(d):
            return []
        return sorted(n for n in os.listdir(d) if _SAFE_FILE.match(n))

    def _read(self, shuffle_id: str, partition_idx: int, name: str) -> bytes:
        if not _SAFE_FILE.match(name):
            raise ValueError(f"bad shuffle file name {name!r}")
        with open(os.path.join(self._pdir(shuffle_id, partition_idx), name), "rb") as f:
            return f.read()

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass


def fetch_partition(endpoints: List[Endpoint], shuffle_id: str, partition_idx: int,
                    schema: Schema) -> Iterator[MicroPartition]:
    """Stream one shuffle partition by fetching every map file from every
    endpoint (the reference's flight-client fan-in, get_flight_client +
    do_get per partition). Fetch volume/latency is recorded into the active
    ShuffleRecorder (shuffle.py) for per-task transport attribution."""
    import time

    from .shuffle import _note_fetch

    for host, port, key_hex in endpoints:
        conn = Client((host, port), family="AF_INET", authkey=bytes.fromhex(key_hex))
        try:
            conn.send(("list", shuffle_id, partition_idx))
            kind, names = conn.recv()
            if kind == "error":
                raise RuntimeError(f"shuffle fetch refused: {names}")
            assert kind == "files", kind
            for name in names:
                t0 = time.perf_counter()
                conn.send(("fetch", shuffle_id, partition_idx, name))
                kind, data = conn.recv()
                if kind == "error":
                    raise RuntimeError(f"shuffle fetch refused: {data}")
                assert kind == "file", kind
                with ipc.RecordBatchFileReader(pa.BufferReader(data)) as r:
                    table = r.read_all()
                batch = RecordBatch.from_arrow(table).cast_to_schema(schema)
                _note_fetch(batch.num_rows, len(data), time.perf_counter() - t0)
                yield MicroPartition(schema, [batch])
            conn.send(("bye",))
        finally:
            conn.close()
