"""Shuffle fetch server: partition files served over authenticated TCP.

Reference parity: src/daft-shuffles/src/server/flight_server.rs:72 (Arrow
Flight `do_get` streams one shuffle partition's files) + client/fetch.rs
fan-in. Here the transport is a multiprocessing.connection TCP listener —
the same HMAC challenge/response machinery the worker tier already uses —
serving the compressed Arrow-IPC stream files written by MapOutputWriter
(shuffle.py).

Topology: every host that runs map tasks starts one ShuffleFetchServer over
its local shuffle directory; reduce tasks fetch each partition from EVERY
endpoint and merge (map outputs for one partition are spread across hosts).
On a single host there is one endpoint, but the fan-in path is identical.

Protocol (pickle frames over the authenticated connection):
    -> ("list",   shuffle_id, partition_idx)         <- ("files", [name, ...])
    -> ("fetch",  shuffle_id, partition_idx, name)   <- ("file", bytes)
    -> ("fetchs", shuffle_id, partition_idx, name)   <- ("part", bytes)* ("end", total)
    -> ("bye",)                                       closes the connection

"fetch" ships a whole file in one frame (the serial compatibility path);
"fetchs" streams it in bounded chunks so the client decodes the first IPC
batch before the last byte arrives. Requests on one connection are served
in order, so a client may PIPELINE: send the request for file k+1 while
still draining file k's chunks — the reply frames never interleave.

The reduce-side fan-in (`fetch_partition`) runs one fetch thread per
endpoint (capped by ExecutionConfig.shuffle_fetch_parallelism), pipelines
requests within each connection, and lands decoded batches in a bounded
queue (shuffle_prefetch_batches) that the reduce iterator drains — network
transfer overlaps reduce compute with real backpressure. With
shuffle_fetch_parallelism=1 and shuffle_prefetch_batches=0 the transport
degrades to the original serial loop: no threads, no queue, one request in
flight.
"""

from __future__ import annotations

import io
import os
import queue as _queue
import re
import secrets
import threading
import time
from multiprocessing import AuthenticationError
from multiprocessing.connection import Client, Listener
from typing import Iterator, List, Optional, Tuple

from ..core.micropartition import MicroPartition
from ..core.recordbatch import RecordBatch
from ..observability.metrics import registry
from ..schema import Schema
from ..utils.env import env_int
from . import faults
from .shuffle import (ShuffleDataLost, ShufflePeerUnreachable, _note_fetch,
                      _note_fetch_wall, check_expected_maps, iter_ipc_batches,
                      partition_dir)

_SAFE_ID = re.compile(r"^[A-Za-z0-9_\-]+$")
_SAFE_FILE = re.compile(r"^m\d+\.arrow$")

# chunk size for the streamed "fetchs" reply — big enough to amortize the
# pickle-frame overhead, small enough that a batch decodes mid-file
_STREAM_CHUNK = 512 * 1024

Endpoint = Tuple[str, int, str]  # (host, port, authkey_hex)


class _FetchAborted(Exception):
    """Internal: the consumer closed the fetch generator (stop event set);
    producer threads unwind promptly instead of blocking in recv() forever
    against a stalled peer — no leaked threads or connection fds."""


def _recv_interruptible(conn, stop):
    """conn.recv() that polls in short slices so a set stop event aborts the
    wait (a blocking recv would never observe it)."""
    while not conn.poll(0.1):
        if stop.is_set():
            raise _FetchAborted()
    return conn.recv()


# transient-connect retry schedule: first retry after _RETRY_BASE_S, doubling,
# capped — a peer mid-restart answers within a few hundred ms; a DEAD peer
# should be classified quickly so map regeneration can start
_RETRY_BASE_S = 0.05
_RETRY_CAP_S = 0.5

_TRANSIENT_CONNECT_ERRORS = (EOFError, OSError)  # OSError covers every Connection*Error


def _fetch_retries() -> int:
    return env_int("DAFT_TPU_FETCH_RETRIES", 2, lo=0)


def _connect_retrying(ep: Endpoint, shuffle_id: str, stop=None):
    """Connect to a fetch peer, retrying refused/reset handshakes with capped
    exponential backoff (DAFT_TPU_FETCH_RETRIES, default 2) so a peer
    mid-restart doesn't immediately classify as dead and trigger map
    regeneration. Exhaustion raises ShufflePeerUnreachable — the signal the
    driver's recovery path regenerates from."""
    host, port, key_hex = ep
    retries = _fetch_retries()
    delay = _RETRY_BASE_S
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            return Client((host, port), family="AF_INET",
                          authkey=bytes.fromhex(key_hex))
        except _TRANSIENT_CONNECT_ERRORS as e:
            last = e
            if attempt >= retries:
                break
            registry().inc("fetch_retries_total")
            if stop is not None:
                if stop.wait(delay):
                    raise _FetchAborted()
            else:
                time.sleep(delay)
            delay = min(delay * 2, _RETRY_CAP_S)
    raise ShufflePeerUnreachable(
        shuffle_id,
        f"shuffle {shuffle_id}: peer {host}:{port} unreachable after "
        f"{retries + 1} attempts ({type(last).__name__}: {last})")


class ShuffleFetchServer:
    """Serves one host's shuffle directory. Thread-per-connection; all state
    is the immutable base path, so concurrent fetches need no locks."""

    def __init__(self, base: str, host: str = "127.0.0.1", port: int = 0,
                 authkey: Optional[bytes] = None):
        self.base = base
        self.authkey = authkey if authkey is not None else secrets.token_bytes(32)
        self._listener = Listener((host, port), family="AF_INET", authkey=self.authkey)
        self._closed = False
        self._threads: List[threading.Thread] = []
        # served-request counters (reference: flight_server metrics); mirrored
        # into the metrics registry so EXPLAIN ANALYZE / bench can attribute
        # transport traffic
        self._stats_lock = threading.Lock()
        self.requests = 0
        self.bytes_served = 0
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="daft-shuffle-fetch")
        t.start()
        self._threads.append(t)

    def _note_request(self, nbytes: int = 0) -> None:
        with self._stats_lock:
            self.requests += 1
            self.bytes_served += nbytes
        registry().inc("shuffle_fetch_server_requests")
        if nbytes:
            registry().inc("shuffle_fetch_server_bytes", nbytes)

    def stats(self) -> dict:
        with self._stats_lock:
            return {"requests": self.requests, "bytes_served": self.bytes_served}

    @property
    def endpoint(self) -> Endpoint:
        host, port = self._listener.address
        return (host, port, self.authkey.hex())

    def _accept_loop(self) -> None:
        # a rejected handshake (bad auth, reset mid-challenge) is per-client
        # and cheap to retry; a PERSISTENT accept error (fd exhaustion,
        # half-closed listener) must not spin the thread hot — back off
        # exponentially, resetting once an accept succeeds again
        backoff = 0.005
        while not self._closed:
            try:
                conn = self._listener.accept()
                backoff = 0.005
            except (OSError, EOFError, AuthenticationError):
                if self._closed:
                    return
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.25)
                continue
            threading.Thread(target=self._serve, args=(conn,), daemon=True,
                             name="daft-shuffle-conn").start()

    def _serve(self, conn) -> None:
        try:
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                if not msg or msg[0] == "bye":
                    return
                try:
                    if msg[0] == "list":
                        _kind, sid, pidx = msg
                        self._note_request()
                        conn.send(("files", self._list(sid, int(pidx))))
                    elif msg[0] == "fetch":
                        _kind, sid, pidx, name = msg
                        data = self._read(sid, int(pidx), name)
                        self._note_request(len(data))
                        conn.send(("file", data))
                    elif msg[0] == "fetchs":
                        _kind, sid, pidx, name = msg
                        total = 0
                        for chunk in self._read_chunks(sid, int(pidx), name):
                            total += len(chunk)
                            conn.send(("part", chunk))
                        conn.send(("end", total))
                        self._note_request(total)
                    else:
                        conn.send(("error", f"unknown request {msg[0]!r}"))
                except Exception as e:  # noqa: BLE001 — refuse the request, keep serving
                    try:
                        conn.send(("error", f"{type(e).__name__}: {e}"))
                    except (BrokenPipeError, OSError):
                        return  # client hung up mid-reply (abandoned fetch)
        finally:
            conn.close()

    def _pdir(self, shuffle_id: str, partition_idx: int) -> str:
        if not _SAFE_ID.match(shuffle_id):
            raise ValueError(f"bad shuffle id {shuffle_id!r}")
        return partition_dir(self.base, shuffle_id, partition_idx)

    def _list(self, shuffle_id: str, partition_idx: int) -> List[str]:
        d = self._pdir(shuffle_id, partition_idx)
        if not os.path.isdir(d):
            return []
        return sorted(n for n in os.listdir(d) if _SAFE_FILE.match(n))

    def _path(self, shuffle_id: str, partition_idx: int, name: str) -> str:
        if not _SAFE_FILE.match(name):
            raise ValueError(f"bad shuffle file name {name!r}")
        return os.path.join(self._pdir(shuffle_id, partition_idx), name)

    def _read(self, shuffle_id: str, partition_idx: int, name: str) -> bytes:
        with open(self._path(shuffle_id, partition_idx, name), "rb") as f:
            return f.read()

    def _read_chunks(self, shuffle_id: str, partition_idx: int,
                     name: str) -> Iterator[bytes]:
        with open(self._path(shuffle_id, partition_idx, name), "rb") as f:
            while True:
                chunk = f.read(_STREAM_CHUNK)
                if not chunk:
                    return
                yield chunk

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass


class _FrameStream(io.RawIOBase):
    """Readable over one "fetchs" reply: ("part", bytes)* then ("end", total).

    Pulls frames from the connection on demand — the IPC stream reader layered
    on top decodes batch k while the server is still sending batch k+1's
    bytes. `drain()` consumes any unread tail so the connection is positioned
    at the next reply (the pipelined request's frames must never leak into
    this file's reader or vice versa)."""

    def __init__(self, conn, stop=None):
        self._conn = conn
        self._stop = stop
        self._buf = b""
        self._eof = False
        self.total = 0     # wire bytes, valid once the "end" frame was seen
        self.received = 0  # wire bytes seen so far (partial-fetch accounting)

    def readable(self) -> bool:
        return True

    def _pump(self) -> None:
        msg = _recv_interruptible(self._conn, self._stop) \
            if self._stop is not None else self._conn.recv()
        kind = msg[0]
        if kind == "part":
            self._buf += msg[1]
            self.received += len(msg[1])
        elif kind == "end":
            self._eof = True
            self.total = int(msg[1])
        elif kind == "error":
            raise RuntimeError(f"shuffle fetch refused: {msg[1]}")
        else:
            raise RuntimeError(f"unexpected shuffle frame {kind!r}")

    def readinto(self, b) -> int:
        while not self._buf:
            if self._eof:
                return 0
            self._pump()
        n = min(len(b), len(self._buf))
        b[:n] = self._buf[:n]
        self._buf = self._buf[n:]
        return n

    def drain(self) -> None:
        while not self._eof:
            self._buf = b""
            self._pump()
        self._buf = b""


def fetch_partition(endpoints: List[Endpoint], shuffle_id: str, partition_idx: int,
                    schema: Schema, parallelism: Optional[int] = None,
                    prefetch: Optional[int] = None,
                    expected_maps=None) -> Iterator[MicroPartition]:
    """Stream one shuffle partition by fetching every map file from every
    endpoint (the reference's flight-client fan-in, get_flight_client +
    do_get per partition). Fetch volume/latency is recorded into the active
    ShuffleRecorder (shuffle.py) for per-task transport attribution.

    `parallelism`/`prefetch` default from ExecutionConfig
    (shuffle_fetch_parallelism / shuffle_prefetch_batches). parallelism<=1
    with prefetch==0 selects the serial compatibility path — one endpoint at
    a time, one whole-file request in flight, no threads, no queue.

    `expected_maps` arms the completeness check: once every endpoint has
    listed its files, any expected map file seen on NO endpoint raises
    ShuffleDataLost (missing files never silently shrink a reduce input).
    Peer failures classify distinctly: a connect that stays refused past the
    retry budget, or a connection reset mid-stream, raises
    ShufflePeerUnreachable — both are the driver's regeneration triggers."""
    if not endpoints:
        check_expected_maps(shuffle_id, expected_maps, ())
        return
    if faults.ENABLED:
        # stage filter resolves via faults.set_stage (worker loop)
        faults.maybe_trip("fetch")
    if parallelism is None or prefetch is None:
        from ..config import execution_config

        cfg = execution_config()
        if parallelism is None:
            parallelism = cfg.shuffle_fetch_parallelism
        if prefetch is None:
            prefetch = cfg.shuffle_prefetch_batches
    if parallelism <= 1 and prefetch == 0:
        inner = _fetch_serial(endpoints, shuffle_id, partition_idx, schema,
                              expected_maps)
    else:
        inner = _fetch_pipelined(endpoints, shuffle_id, partition_idx,
                                 schema, parallelism, prefetch, expected_maps)
    # timeline profiling: one "shuffle.fetch" slice per partition fan-in,
    # covering the whole consumption window (transfer overlapped with the
    # consumer's reduce work — the wall window, same axis as fetch_wall)
    from ..observability.runtime_stats import span_iter

    yield from span_iter("shuffle.fetch", "io", inner,
                         shuffle_id=shuffle_id, partition=partition_idx,
                         endpoints=len(endpoints))


def _fetch_serial(endpoints: List[Endpoint], shuffle_id: str, partition_idx: int,
                  schema: Schema, expected_maps=None) -> Iterator[MicroPartition]:
    """The original serial transport: every file from every endpoint, one
    request at a time over one connection. Batches still decode one IPC
    message at a time (bounded memory), but nothing overlaps."""
    seen: set = set()
    for ep in endpoints:
        host, port, _key = ep
        conn = _connect_retrying(ep, shuffle_id)

        def _peer_io(fn, *args):
            # sends fail too (BrokenPipeError on a dead peer), not just
            # recvs: every wire op on an established connection classifies
            # uniformly so the driver regenerates instead of failing
            try:
                return fn(*args)
            except (EOFError, OSError) as e:
                raise ShufflePeerUnreachable(
                    shuffle_id, f"shuffle {shuffle_id}: peer {host}:{port} "
                                f"connection lost mid-fetch ({e})")

        try:
            _peer_io(conn.send, ("list", shuffle_id, partition_idx))
            kind, names = _peer_io(conn.recv)
            if kind == "error":
                raise RuntimeError(f"shuffle fetch refused: {names}")
            assert kind == "files", kind
            seen.update(names)
            for name in names:
                t0 = time.perf_counter()
                _peer_io(conn.send, ("fetch", shuffle_id, partition_idx, name))
                kind, data = _peer_io(conn.recv)
                if kind == "error":
                    raise RuntimeError(f"shuffle fetch refused: {data}")
                assert kind == "file", kind
                # yield each batch as it decodes (peak memory: the wire bytes
                # plus ONE decoded batch); segmented timing keeps the
                # consumer's processing between yields out of fetch_seconds.
                # The finally records even when the consumer closes the
                # generator mid-file — the wire bytes WERE transferred
                rows = 0
                spent = 0.0
                t_seg = t0
                try:
                    for rb in iter_ipc_batches(io.BytesIO(data)):
                        batch = RecordBatch.from_arrow(rb).cast_to_schema(schema)
                        rows += batch.num_rows
                        spent += time.perf_counter() - t_seg
                        yield MicroPartition(schema, [batch])
                        t_seg = time.perf_counter()
                    spent += time.perf_counter() - t_seg
                finally:
                    _note_fetch(rows, len(data), spent)
            try:
                conn.send(("bye",))
            except (EOFError, OSError):
                pass  # courtesy close only — every file already arrived
        finally:
            conn.close()
    check_expected_maps(shuffle_id, expected_maps, seen)


def _fetch_pipelined(endpoints: List[Endpoint], shuffle_id: str,
                     partition_idx: int, schema: Schema, parallelism: int,
                     prefetch: int, expected_maps=None) -> Iterator[MicroPartition]:
    """Parallel multi-peer fetch with bounded prefetch.

    One thread per endpoint (endpoints round-robined when there are more than
    `parallelism`), each pipelining chunk-streamed "fetchs" requests on its
    connection (the request for file k+1 is sent before file k finishes
    decoding). Decoded batches land in a bounded queue the caller drains —
    the queue depth, not the map-file size, bounds reduce-side memory, and a
    slow consumer backpressures the network naturally.

    Overlap accounting: each request's in-flight time runs from its send to
    its last decoded byte, NET of time this connection spent blocked on the
    full prefetch queue (consumer backpressure is reduce compute, not
    transfer, and must not masquerade as fetch time); summed over requests
    this over-counts the union transfer window by the seconds two requests
    were in flight together — `shuffle_overlap_seconds`."""
    n_threads = min(max(parallelism, 1), len(endpoints))
    groups = [endpoints[i::n_threads] for i in range(n_threads)]
    q: _queue.Queue = _queue.Queue(maxsize=max(prefetch, 1))
    stop = threading.Event()
    agg_lock = threading.Lock()
    agg = {"cum": 0.0, "first_send": None, "last_end": None, "hw": 0}
    seen: set = set()  # file names listed across every endpoint (agg_lock)
    from ..memory.manager import manager

    # budgeted reduce: a fetch thread stuck on the full prefetch queue may
    # DIVERT to a spill file instead of blocking — decoded batches keep
    # landing on disk at transfer speed rather than stalling the peer, and
    # the consumer drains the overflow (prefetching reader) after the
    # thread's queued batches. Unbudgeted queries never divert (and so never
    # touch the spill pool): the queue block IS the backpressure contract.
    divert_ok = manager().limit_bytes() > 0

    def _put(item) -> bool:
        # never block forever: a consumer that stopped draining (closed
        # generator) sets `stop`, and the producer gives up instead of
        # leaking a thread wedged in put()
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _put_or_divert(item) -> str:
        # "ok" | "stopped" | "divert" — divert only after the queue has been
        # full long enough that this is sustained consumer backpressure,
        # not a transient blip
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return "ok"
            except _queue.Full:
                if divert_ok and time.perf_counter() - t0 > 0.25:
                    return "divert"
        return "stopped"

    def _note_send(t: float) -> None:
        with agg_lock:
            if agg["first_send"] is None or t < agg["first_send"]:
                agg["first_send"] = t

    def _note_done(in_flight: float, t_end: float) -> None:
        with agg_lock:
            agg["cum"] += in_flight
            if agg["last_end"] is None or t_end > agg["last_end"]:
                agg["last_end"] = t_end

    def _fetch_endpoint(ep: Endpoint, spill: dict) -> None:
        host, port, _key = ep
        conn = _connect_retrying(ep, shuffle_id, stop)
        try:
            conn.send(("list", shuffle_id, partition_idx))
            # socket-level failures propagate to _run, which classifies them
            # as ShufflePeerUnreachable — one classification site, not three
            kind, names = _recv_interruptible(conn, stop)
            if kind == "error":
                raise RuntimeError(f"shuffle fetch refused: {names}")
            assert kind == "files", kind
            with agg_lock:
                seen.update(names)
            if not names:
                try:
                    conn.send(("bye",))
                except (EOFError, OSError):
                    pass  # courtesy close only — nothing was owed
                return
            send_at: dict = {}
            sent_blocked: dict = {}
            # cumulative seconds THIS connection spent blocked on the full
            # prefetch queue — consumer backpressure, subtracted from every
            # request clock spanning it so reduce compute never masquerades
            # as fetch/overlap time
            tally = {"blocked": 0.0}

            def _send_req(i: int) -> None:
                send_at[i] = time.perf_counter()
                sent_blocked[i] = tally["blocked"]
                _note_send(send_at[i])
                conn.send(("fetchs", shuffle_id, partition_idx, names[i]))

            _send_req(0)
            for i in range(len(names)):
                if i + 1 < len(names):
                    # pipeline: file k+1's request rides behind file k's
                    # reply frames; the server serves in order
                    _send_req(i + 1)
                frames = _FrameStream(conn, stop)
                rows = 0
                for rb in iter_ipc_batches(io.BufferedReader(frames)):
                    batch = RecordBatch.from_arrow(rb).cast_to_schema(schema)
                    rows += batch.num_rows
                    if spill["f"] is not None:
                        # this thread already diverted: all later batches
                        # follow (per-thread arrival order is preserved —
                        # the overflow file replays after the queued prefix)
                        spill["f"].append(batch)
                        registry().inc("shuffle_reduce_spill_bytes",
                                       batch.size_bytes())
                        continue
                    t_put = time.perf_counter()
                    res = _put_or_divert(("batch",
                                          MicroPartition(schema, [batch])))
                    if res == "stopped":
                        # consumer gone mid-file: account the transfer that
                        # DID happen (received wire bytes, decoded rows)
                        # before unwinding
                        _note_fetch(rows, frames.received, max(
                            (time.perf_counter() - send_at[i])
                            - (tally["blocked"] - sent_blocked[i]), 0.0))
                        return
                    tally["blocked"] += time.perf_counter() - t_put
                    if res == "divert":
                        from ..memory.spill import SpillFile

                        spill["f"] = SpillFile(schema)
                        spill["f"].append(batch)
                        registry().inc("shuffle_reduce_spill_bytes",
                                       batch.size_bytes())
                        continue
                    sz = q.qsize()
                    with agg_lock:
                        if sz > agg["hw"]:
                            agg["hw"] = sz
                frames.drain()  # position the connection at the next reply
                t_end = time.perf_counter()
                in_flight = max(
                    (t_end - send_at[i])
                    - (tally["blocked"] - sent_blocked[i]), 0.0)
                _note_done(in_flight, t_end)
                _note_fetch(rows, frames.total, in_flight)
            try:
                conn.send(("bye",))
            except (EOFError, OSError):
                pass  # courtesy close only — every file already arrived; a
                # peer exiting now must NOT classify as unreachable (that
                # would trigger spurious full-shuffle regeneration)
        finally:
            conn.close()

    def _run(eps: List[Endpoint]) -> None:
        spill = {"f": None}  # this thread's overflow file, once diverted
        try:
            for ep in eps:
                if stop.is_set():
                    return
                try:
                    _fetch_endpoint(ep, spill)
                except (EOFError, OSError) as e:
                    # peer vanished mid-stream (EOF, reset, broken pipe,
                    # timeout — ANY socket-level failure on an established
                    # connection): classify distinctly so the driver
                    # regenerates instead of failing the query
                    host, port, _k = ep
                    raise ShufflePeerUnreachable(
                        shuffle_id,
                        f"shuffle {shuffle_id}: peer {host}:{port} "
                        f"connection lost mid-fetch ({e})")
            if spill["f"] is not None:
                # hand the overflow to the consumer (it deletes after replay)
                if _put(("spill", spill["f"])):
                    spill["f"] = None
            _put(("done", None))
        except _FetchAborted:
            return  # consumer closed the generator; nothing to report
        except Exception as e:  # noqa: BLE001 — crossed to the consumer, re-raised there
            _put(("err", e))
        finally:
            if spill["f"] is not None:
                spill["f"].delete()  # never handed off: clean up here

    threads = [threading.Thread(target=_run, args=(g,), daemon=True,
                                name="daft-shuffle-fetch-client")
               for g in groups]
    for t in threads:
        t.start()
    try:
        done = 0
        while done < len(threads):
            kind, payload = q.get()
            if kind == "done":
                done += 1
            elif kind == "err":
                if isinstance(payload, (ShuffleDataLost, ShufflePeerUnreachable)):
                    raise payload  # typed recovery triggers survive the fan-in
                raise RuntimeError(f"shuffle fetch failed: {payload}") from payload
            elif kind == "spill":
                # replay one thread's diverted overflow (prefetching reader)
                try:
                    for b in payload.read():
                        yield MicroPartition(schema, [b])
                finally:
                    payload.delete()
            else:
                yield payload
        with agg_lock:
            listed = set(seen)
        check_expected_maps(shuffle_id, expected_maps, listed)
    finally:
        stop.set()
        while True:  # unblock producers wedged in put()
            try:
                kind, payload = q.get_nowait()
            except _queue.Empty:
                break
            if kind == "spill":
                payload.delete()  # overflow never replayed: remove the file
        for t in threads:
            t.join(timeout=5)
        with agg_lock:
            cum, hw = agg["cum"], agg["hw"]
            window = (agg["last_end"] - agg["first_send"]) \
                if agg["first_send"] is not None and agg["last_end"] is not None \
                else 0.0
        _note_fetch_wall(window, n_threads, max(cum - window, 0.0))
        registry().set_gauge_max("shuffle_fetch_inflight", hw)
