"""Worker processes: subprocess + UNIX-socket task executors.

Reference parity: the RaySwordfishActor worker (daft/runners/flotilla.py:112 —
one stateless executor per node that runs serialized sub-plans) behind the
WorkerManager dispatch boundary (src/daft-distributed/src/scheduling/worker.rs:38),
with the reference's subprocess+socket transport (daft/execution/udf.py:57).

Workers are fresh ``python -m daft_tpu.distributed.worker`` subprocesses that
connect back to the driver's UNIX socket (multiprocessing.connection framing,
pickle payloads). NOT fork (the parent holds a multithreaded JAX runtime —
forking it deadlocks, VERDICT r2 weak #7) and NOT multiprocessing.spawn (which
re-executes ``__main__`` and breaks REPL/stdin drivers). Workers never touch
the device: DAFT_TPU_DEVICE=off is set in their environment so sub-plans
containing Device*Agg nodes take the host path.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time
import traceback
import uuid
from collections import deque
from multiprocessing import AuthenticationError
from multiprocessing.connection import Client, Listener
from typing import Dict, List, Optional

from ..observability.metrics import registry
from ..utils.env import env_bool, env_float, env_int
from . import faults
from .task import SubPlanTask, TaskResult


def _rss_bytes() -> int:
    """Resident set size of this process (linux /proc; getrusage fallback)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE") or 4096)
    except (OSError, ValueError, IndexError):
        try:
            import resource

            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:  # lint: ignore[broad-except] -- heartbeat must never fail the worker
            return 0


def _residency_module():
    """The already-imported residency module, or None — the heartbeat thread
    must NEVER trigger an import itself: the main thread's first task import
    (daft_tpu executor + jax, seconds on a cold cache) holds per-module import
    locks, and a heartbeat thread blocked on them falls silent exactly long
    enough for the driver's liveness monitor to declare this worker dead."""
    import sys

    return sys.modules.get("daft_tpu.device.residency") \
        or sys.modules.get(f"{__package__.rsplit('.', 1)[0]}.device.residency")


def _hbm_bytes() -> int:
    """Device bytes held by this worker's HBM residency manager (0 when the
    worker never touched a device)."""
    try:
        mod = _residency_module()
        return mod.manager().bytes_resident() if mod is not None else 0
    except Exception:  # lint: ignore[broad-except] -- heartbeat must never fail the worker
        return 0


def _hbm_digest() -> list:
    """Compact residency digest: (stable_slot_key, bytes) pairs for the device
    planes this worker holds (capped). The driver drains these into scheduler
    WorkerSnapshots for cache-affinity placement."""
    try:
        mod = _residency_module()
        return mod.manager().digest() if mod is not None else []
    except Exception:  # lint: ignore[broad-except] -- heartbeat must never fail the worker
        return []


def _hbm_h2d_bytes() -> int:
    """Cumulative host->device upload bytes in this worker (hbm_h2d_bytes
    counter) — a repeat sub-plan served from resident planes shows a zero
    delta, which the affinity tests assert end to end."""
    try:
        return registry().get("hbm_h2d_bytes")
    except Exception:  # lint: ignore[broad-except] -- heartbeat must never fail the worker
        return 0


def _run_task(task: SubPlanTask, worker_id: str) -> TaskResult:
    """Execute one sub-plan. When the task asks for stats (driver has
    subscribers attached or explain_analyze running) the plan runs under a
    StatsCollector with a ShuffleRecorder installed, and the result ships the
    per-operator stats + shuffle volume + a task span id within the stamped
    trace context back to the driver."""
    from ..execution.executor import execute_plan
    from . import shuffle as shf

    collector = recorder = span_rec = None
    reg_before = None
    # map-output lineage sink: installed for EVERY task (not just traced
    # ones) — the driver's reduce-side completeness check and the lost-map
    # regeneration path depend on these records, so they are correctness
    # state, not telemetry. Costs one list per task.
    map_sink: list = []
    shf.set_map_outputs(map_sink)
    if task.collect_stats:
        from ..observability.metrics import registry
        from ..observability.otlp import _span_id
        from ..observability.runtime_stats import (SpanRecorder, StatsCollector,
                                                   set_collector, set_spans)

        collector = StatsCollector()
        recorder = shf.ShuffleRecorder()
        span_rec = SpanRecorder()
        reg_before = registry().snapshot()
        set_collector(collector)
        set_spans(span_rec)
        shf.set_recorder(recorder)
    started_at = time.time()
    t0 = time.perf_counter()
    try:
        plan = task.plan()
        parts = [p for p in execute_plan(plan)]
        exec_s = time.perf_counter() - t0
        rows = sum(p.num_rows for p in parts)
        res = TaskResult(task_id=task.task_id, worker_id=worker_id,
                         partitions=parts, rows=rows,
                         exec_seconds=exec_s, started_at=started_at)
        res.map_outputs = tuple(map_sink)
        if collector is not None:
            res.bytes_out = sum(p.size_bytes() for p in parts)
            res.op_stats = tuple(collector.finish())
            res.shuffle = recorder.as_dict()
            # timeline spans (device dispatch/h2d/d2h, shuffle fetch) in
            # worker-clock unix time; the driver's QueryTrace re-aligns them
            res.spans = tuple(span_rec.drain())
            res.span_id = _span_id(task.trace_id or task.task_id,
                                   "task", task.task_id)
            from ..observability.metrics import registry

            # which engine paths THIS task took in THIS process (device
            # dispatches, coalescing, HBM traffic) — per-operator stats can't
            # carry that; see TaskResult.engine_counters
            res.engine_counters = registry().diff(reg_before)
        return res
    finally:
        shf.set_map_outputs(None)
        if task.collect_stats:
            from ..observability.runtime_stats import set_collector, set_spans

            set_collector(None)
            set_spans(None)
            shf.set_recorder(None)


def _classify_error(e: BaseException):
    """(error_kind, error_data) for recoverable failure classes the driver
    can act on; ("", None) for everything else."""
    from . import shuffle as shf

    if isinstance(e, shf.ShuffleDataLost):
        return "shuffle_data_lost", {"shuffle_id": e.shuffle_id,
                                     "map_ids": list(e.map_ids)}
    if isinstance(e, shf.ShufflePeerUnreachable):
        return "shuffle_peer_unreachable", {"shuffle_id": e.shuffle_id}
    return "", None


def _worker_loop(conn, worker_id: str) -> None:
    """Receive pickled SubPlanTasks, execute, reply TaskResult. A background
    thread interleaves ("heartbeat", {...}) reports — slot occupancy, task
    counts, RSS — on the same connection (send-locked; the driver routes them
    out of band in WorkerProcess.poll)."""
    send_lock = threading.Lock()
    stop = threading.Event()
    state = {"busy": 0, "completed": 0, "failed": 0}
    t_start = time.time()

    def _send(msg) -> None:
        # serialize OUTSIDE the lock: pickling a large TaskResult can take
        # whole seconds, and the heartbeat thread shares this lock — holding
        # it through the dumps would silence beats long enough for the
        # driver's liveness monitor to SIGKILL a healthy worker mid-send.
        # send_bytes(ForkingPickler.dumps(x)) is exactly what conn.send(x)
        # does internally, so the driver's recv() decodes it unchanged.
        from multiprocessing.reduction import ForkingPickler

        buf = bytes(ForkingPickler.dumps(msg))
        with send_lock:
            # lint: ignore[blocking-under-lock] -- send_lock exists to serialize
            # this pipe; the payload is pre-pickled so the hold is one write
            conn.send_bytes(buf)

    total_slots = env_int("DAFT_TPU_WORKER_SLOTS", 1, lo=1)

    def _heartbeat_loop(interval: float) -> None:
        # first beat immediately so even sub-second queries observe >=1
        while not stop.is_set():
            try:
                _send(("heartbeat", {
                    "worker_id": worker_id, "ts": time.time(),
                    "busy_slots": state["busy"], "total_slots": total_slots,
                    "tasks_completed": state["completed"],
                    "tasks_failed": state["failed"],
                    "rss_bytes": _rss_bytes(),
                    "hbm_bytes_resident": _hbm_bytes(),
                    "hbm_digest": _hbm_digest(),
                    "hbm_h2d_bytes": _hbm_h2d_bytes(),
                    "uptime_s": time.time() - t_start,
                }))
            except (BrokenPipeError, OSError):
                return  # driver gone; main loop will notice on recv
            stop.wait(interval)

    _send(("hello", worker_id))
    interval = env_float("DAFT_TPU_HEARTBEAT_S", 2.0)
    if interval > 0:
        threading.Thread(target=_heartbeat_loop, args=(interval,),
                         daemon=True, name="daft-heartbeat").start()
    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, KeyboardInterrupt):
                return
            if msg is None or msg[0] == "stop":
                return
            kind, task = msg
            assert kind == "task"
            state["busy"] = 1
            try:
                if faults.ENABLED:
                    faults.set_stage(task.stage_id)
                    faults.maybe_trip("task_start", stage_id=task.stage_id)
                res = _run_task(task, worker_id)
                state["completed"] += 1
                _send(res)
                if faults.ENABLED:
                    # the post-publish window: the task's result is already on
                    # the wire, so a trip here simulates a host that finished
                    # its map work and THEN died (optionally taking its
                    # shuffle files with it — the regeneration trigger)
                    faults.maybe_trip(
                        "task_sent", stage_id=task.stage_id,
                        paths=[p for mo in res.map_outputs
                               for p in mo.get("paths", ())])
            except Exception as e:  # noqa: BLE001 — errors must cross the process boundary
                state["failed"] += 1
                err_kind, err_data = _classify_error(e)
                _send(TaskResult(task_id=task.task_id, worker_id=worker_id,
                                 error=f"{type(e).__name__}: {e}",
                                 error_tb=traceback.format_exc(),
                                 error_kind=err_kind, error_data=err_data))
            finally:
                state["busy"] = 0
    finally:
        stop.set()


def main(argv: List[str]) -> None:
    address, worker_id = argv[0], argv[1]
    # exported so fault tripwires (faults.py) can target one worker by id
    os.environ["DAFT_TPU_WORKER_ID"] = worker_id
    authkey = bytes.fromhex(os.environ["DAFT_TPU_WORKER_AUTHKEY"])
    conn = Client(address, family="AF_UNIX", authkey=authkey)
    try:
        _worker_loop(conn, worker_id)
    finally:
        conn.close()


class WorkerProcess:
    """Handle to one worker subprocess (the WorkerHandle the scheduler targets)."""

    def __init__(self, worker_id: str, acceptor, address: str, slots: int = 1,
                 env: Optional[Dict[str, str]] = None):
        self.worker_id = worker_id
        self.slots = slots
        # the extra env this worker was spawned with (device lease, fault
        # tripwires): a respawned replacement must inherit it, or a dead
        # device-leased worker comes back host-only and the pool silently
        # loses device capability for its remaining lifetime
        self.spawn_env: Dict[str, str] = dict(env or {})
        child_env = dict(os.environ)
        child_env.setdefault("DAFT_TPU_DEVICE", "off")
        child_env["DAFT_TPU_WORKER_SLOTS"] = str(slots)
        # workers retain content-addressed device planes past their transient
        # per-task anchors (device/residency.py orphan policy): a repeat
        # sub-plan rebinds them instead of re-uploading. The HBM budget still
        # bounds total bytes; this caps the orphaned ENTRY count.
        child_env.setdefault("DAFT_TPU_HBM_ORPHANS", "256")
        # make the engine AND everything the driver can import resolvable in
        # the child (script dir, pytest-inserted test dirs): shipped sub-plans
        # may reference classes from any module on the driver's sys.path
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        prev = child_env.get("PYTHONPATH", "")
        paths = [pkg_root] + [p for p in sys.path if p and p != pkg_root]
        if prev:
            paths.append(prev)
        child_env["PYTHONPATH"] = os.pathsep.join(paths)
        child_env.update(env or {})
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "daft_tpu.distributed._worker_entry",
             address, worker_id],
            env=child_env)
        # accept with a liveness check and a hard deadline: a child that
        # crashes on startup (or a stranger stalling the auth handshake) must
        # never hang the driver in accept()
        # the acceptor is shared pool-wide, so an accepted connection may
        # belong to a sibling worker — route by the hello's worker id
        routed = getattr(acceptor, "routed_hellos", None)
        if routed is None:
            routed = {}
            acceptor.routed_hellos = routed
        deadline = 60.0
        self._conn = None
        while self._conn is None:
            if worker_id in routed:
                self._conn = routed.pop(worker_id)
                break
            try:
                conn = acceptor.accept(0.5)
            except AuthenticationError:
                conn = None  # stranger with the wrong key; keep waiting
            if conn is not None:
                if not conn.poll(30):
                    self._proc.terminate()
                    raise RuntimeError("worker connection never sent hello")
                hello = conn.recv()
                assert hello[0] == "hello", hello
                if hello[1] == worker_id:
                    self._conn = conn
                else:
                    routed[hello[1]] = conn
                continue
            rc = self._proc.poll()
            if rc is not None:
                raise RuntimeError(
                    f"worker {worker_id} exited with code {rc} before connecting")
            deadline -= 0.5
            if deadline <= 0:
                self._proc.terminate()
                raise RuntimeError(f"worker {worker_id} never connected (60s)")
        self.inflight: Dict[str, SubPlanTask] = {}
        # out-of-band worker heartbeats received during poll (bounded window)
        self.heartbeats: deque = deque(maxlen=256)
        # results received while draining heartbeats; poll() serves these first
        self._pending_results: deque = deque()
        # latest residency digest from a heartbeat: stable slot key -> bytes
        # (scheduler cache-affinity input; survives heartbeat window drains).
        # digest_seq bumps on every refresh so the dispatch loop pushes the
        # digest to the scheduler only when it actually changed
        self.last_digest: Dict[int, int] = {}
        self.digest_seq = 0
        # most recent heartbeat payload, surviving window drains: a warm pool
        # can finish a whole query in less than one heartbeat period, and the
        # runner falls back to this so /api/workers never shows an empty pool
        # after a sub-period query
        self.last_hb: Optional[dict] = None
        # multiprocessing.Connection framing is not thread-safe: the pool's
        # dispatcher thread polls while a driver thread may drain heartbeats
        # (concurrent serving queries), so every send/recv on this connection
        # goes through one lock
        self._io_lock = threading.RLock()
        # ---- liveness state (driver-side failure detection) -----------------
        # last time ANY traffic arrived from this worker (heartbeat or
        # result): results prove liveness as much as beats do, and a poll()
        # returning a result may leave beats buffered behind it — judging by
        # beats alone would false-positive on a busy, healthy worker
        self.last_beat = time.time()
        # the connection EOF'd while the process still looks alive (hung
        # worker that closed its socket) — treated as a failure by the pool
        self.conn_dead = False
        # set by WorkerPool when the liveness monitor declares this worker
        # dead (heartbeat timeout / connection EOF); the reason string flows
        # to counters, the query trace, and the dashboard's dead-worker list
        self.failed_reason: Optional[str] = None

    def mark_failed(self, reason: str) -> None:
        """Declare this worker dead: record the reason and SIGKILL the
        process (SIGKILL acts even on a SIGSTOP'd process — the case the
        heartbeat timeout exists to catch)."""
        if self.failed_reason is None:
            self.failed_reason = reason
        try:
            self._proc.kill()
        except OSError:
            pass

    def submit(self, task: SubPlanTask) -> None:
        with self._io_lock:
            self.inflight[task.task_id] = task
            # lint: ignore[blocking-under-lock] -- _io_lock exists to serialize
            # this conn (PR 8); tasks are small and no liveness path shares it
            self._conn.send(("task", task))

    def _note_heartbeat(self, hb: dict) -> None:
        # driver-side receive stamp: recv_ts - ts (worker send clock) over a
        # query's beats lower-bounds to the worker->driver clock offset used
        # to align worker span timestamps in the Chrome trace export
        hb = dict(hb)
        hb["recv_ts"] = time.time()
        self.heartbeats.append(hb)
        self.last_hb = hb
        digest = hb.get("hbm_digest")
        if digest is not None:
            self.last_digest = dict(digest)
            self.digest_seq += 1

    def poll(self, timeout: float = 0.0) -> Optional[TaskResult]:
        with self._io_lock:
            if self._pending_results:
                res = self._pending_results.popleft()
                self.inflight.pop(res.task_id, None)
                return res
            try:
                while self._conn.poll(timeout):
                    # lint: ignore[blocking-under-lock] -- poll() said data is
                    # ready; _io_lock serializes this conn by design (PR 8)
                    msg = self._conn.recv()
                    self.last_beat = time.time()  # any traffic = alive
                    if isinstance(msg, tuple) and msg and msg[0] == "heartbeat":
                        # out-of-band heartbeat: record and keep draining
                        # (without blocking again — the result may already be
                        # queued)
                        self._note_heartbeat(msg[1])
                        timeout = 0.0
                        continue
                    res: TaskResult = msg
                    self.inflight.pop(res.task_id, None)
                    return res
            except (EOFError, BrokenPipeError, OSError):
                # dead worker: the pool's liveness pass re-queues its
                # in-flight tasks (conn_dead catches the hung-but-running
                # process whose exit code never changes)
                self.conn_dead = True
            return None

    def pump(self) -> None:
        """Drain whatever the connection holds without consuming anything:
        heartbeats land in the window (and refresh last_digest), results are
        stashed for the next poll(). Lets the pool refresh residency digests
        before scheduling a stage."""
        with self._io_lock:
            try:
                while self._conn.poll(0.0):
                    # lint: ignore[blocking-under-lock] -- zero-timeout poll()
                    # said data is ready; _io_lock serializes this conn
                    msg = self._conn.recv()
                    self.last_beat = time.time()
                    if isinstance(msg, tuple) and msg and msg[0] == "heartbeat":
                        self._note_heartbeat(msg[1])
                    else:
                        self._pending_results.append(msg)
            except (EOFError, BrokenPipeError, OSError):
                self.conn_dead = True

    def drain_heartbeats(self) -> List[dict]:
        """Non-destructively empty the connection: heartbeats are collected;
        any TaskResult encountered is stashed for the next poll() (a stale
        result from an errored stage must not be silently consumed here)."""
        with self._io_lock:
            self.pump()
            out = list(self.heartbeats)
            self.heartbeats.clear()
            return out

    @property
    def alive(self) -> bool:
        return self._proc.poll() is None

    def stop(self) -> None:
        try:
            if self.alive:
                with self._io_lock:
                    # lint: ignore[blocking-under-lock] -- shutdown path; the
                    # lock serializes the conn and nothing else is running
                    self._conn.send(("stop",))
                self._proc.wait(timeout=2)
        except (BrokenPipeError, OSError, subprocess.TimeoutExpired):
            pass
        finally:
            if self.alive:
                self._proc.terminate()
                try:
                    self._proc.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
            try:
                self._conn.close()
            except OSError:
                pass


class _StageRun:
    """One run_tasks() call in flight on the pool dispatcher: the caller
    thread waits on `done` while the dispatcher routes this stage's results
    here. `key` is the scheduler stream key — one per concurrent stage, so
    the per-stream round-robin in Scheduler.schedule() interleaves concurrent
    queries' tasks fairly across the shared workers."""

    __slots__ = ("key", "stage_id", "trace", "tasks", "expected", "results",
                 "error", "error_kind", "error_data", "done",
                 "completed_times", "running", "speculated",
                 "dup_worker", "dispatched_at", "stats_before",
                 "placement_stats")

    def __init__(self, key: str, tasks: List[SubPlanTask], stage_id: str,
                 trace) -> None:
        self.key = key
        self.stage_id = stage_id
        self.trace = trace
        self.tasks: Dict[str, SubPlanTask] = {t.task_id: t for t in tasks}
        self.expected = set(self.tasks)
        self.results: Dict[str, TaskResult] = {}
        self.error: Optional[str] = None
        # structured classification of the failing task's error (see
        # TaskResult.error_kind): run_tasks re-raises typed exceptions from
        # these so the planner's recovery loop can regenerate lost shuffle
        # maps instead of failing the whole query
        self.error_kind: str = ""
        self.error_data: Optional[dict] = None
        self.done = threading.Event()
        self.completed_times: List[float] = []   # exec seconds (speculation median)
        self.running: Dict[str, tuple] = {}      # task_id -> (worker_id, dispatch ts)
        self.speculated: set = set()
        self.dup_worker: Dict[str, str] = {}     # task_id -> speculative copy's worker
        self.dispatched_at: Dict[str, float] = {}
        self.stats_before: Dict[str, int] = {}
        self.placement_stats: Dict[str, int] = {}

    def fail(self, error: str, kind: str = "",
             data: Optional[dict] = None) -> None:
        self.error = error
        self.error_kind = kind
        self.error_data = data
        self.done.set()


class WorkerPool:
    """N local workers + scheduler-driven dispatch with failure re-queue.

    run_tasks() drives a stage to completion and is safe to call from
    CONCURRENT driver threads (the serving tier runs several distributed
    queries over one pool): all worker-connection I/O and scheduling run on a
    single pool-level dispatcher thread; each run_tasks call registers a
    _StageRun and waits. The shared Scheduler deals pending tasks round-robin
    across concurrent stages, re-queues tasks whose worker died (excluding
    that worker, like the reference's snapshot-based retry), and raises the
    original traceback for task-level errors.

    Failure detection (elastic fault tolerance): workers heartbeat on their
    connections; the dispatcher declares a worker DEAD on process exit,
    connection EOF, or DAFT_TPU_HEARTBEAT_TIMEOUT_S of silence (default ~= 3
    missed DAFT_TPU_HEARTBEAT_S beats — catches SIGSTOP'd/hung workers that
    neither exit nor EOF). A dead worker's in-flight tasks requeue with it
    excluded (worker_failures_total / tasks_requeued_total), and with
    DAFT_TPU_WORKER_RESPAWN > 0 the pool spawns up to that many replacements
    over its lifetime, spaced by a doubling backoff.

    Speculative re-execution (the action half of QueryTrace.straggler_report):
    once a stage has >= 2 finished tasks, a still-running task whose elapsed
    time exceeds DAFT_TPU_STRAGGLER_K x the stage's completed-task median
    (and a floor, DAFT_TPU_SPECULATIVE_MIN_S) is duplicate-dispatched to a
    different worker; the first result wins and the loser is discarded.
    DAFT_TPU_SPECULATIVE=0 disables. Shuffle map duplicates are safe because
    MapOutputWriter publishes atomically (write-temp + rename, identical
    deterministic content).
    """

    def __init__(self, num_workers: int, slots_per_worker: int = 1,
                 env: Optional[Dict[str, str]] = None,
                 max_workers: Optional[int] = None,
                 device_workers: int = 0,
                 device_mode: Optional[str] = None):
        sock = os.path.join(tempfile.gettempdir(),
                            f"daft_tpu_{os.getpid()}_{uuid.uuid4().hex[:8]}.sock")
        # HMAC-authenticated socket: only processes holding the per-pool
        # secret (passed via the child environment) can deliver pickles
        authkey = os.urandom(32)
        self._listener = Listener(sock, family="AF_UNIX", authkey=authkey)
        env = dict(env or {})
        env["DAFT_TPU_WORKER_AUTHKEY"] = authkey.hex()
        # Batching/coalescing config plumbing: workers read ExecutionConfig
        # from THEIR environment, so a driver-side set_execution_config(...)
        # (not expressed as env vars) would silently not reach sub-plans.
        # Mirror the driver's effective knobs into the children; an explicit
        # `env=` entry passed by the caller still wins (setdefault). Like the
        # device lease below, the knobs are FIXED at pool construction
        # (subprocess env): a config change after the pool exists applies to
        # driver-side planning/costing but not to already-spawned workers —
        # recreate the runner/pool to re-lease the new knobs.
        from ..config import execution_config

        cfg = execution_config()
        env.setdefault("DAFT_TPU_BATCHING", cfg.batching_mode)
        env.setdefault("DAFT_TPU_BATCH_FILL", str(cfg.batch_fill_target))
        env.setdefault("DAFT_TPU_BATCH_LATENCY_MS", str(cfg.batch_latency_ms))
        env.setdefault("DAFT_TPU_MORSEL_SIZE", str(cfg.morsel_size_rows))
        # shuffle transport knobs: map tasks write (compression) and reduce
        # tasks fetch (fan-in parallelism, prefetch depth) in WORKER
        # processes, so the driver's effective knobs must reach them the same
        # way the batching knobs do
        env.setdefault("DAFT_TPU_SHUFFLE_COMPRESSION", cfg.shuffle_compression)
        env.setdefault("DAFT_TPU_SHUFFLE_FETCH_PARALLELISM",
                       str(cfg.shuffle_fetch_parallelism))
        env.setdefault("DAFT_TPU_SHUFFLE_PREFETCH",
                       str(cfg.shuffle_prefetch_batches))
        # spill IO knobs: budgeted reduce tasks spill and prefetch in worker
        # processes (fetch-queue diversion, spill read-back), so the
        # driver's async-spill configuration must follow them too
        env.setdefault("DAFT_TPU_SPILL_IO_THREADS", str(cfg.spill_io_threads))
        env.setdefault("DAFT_TPU_SPILL_PREFETCH_BATCHES",
                       str(cfg.spill_prefetch_batches))
        # heartbeat cadence: driver (liveness timeout) and workers (beat
        # interval) must agree — mirror the effective interval into the
        # children; an explicit env entry passed by the caller wins
        hb = env_float("DAFT_TPU_HEARTBEAT_S", 2.0)
        try:
            hb = float(env.get("DAFT_TPU_HEARTBEAT_S", hb))
        except ValueError:
            pass
        env.setdefault("DAFT_TPU_HEARTBEAT_S", str(hb))
        self._hb_interval = hb
        from ..utils.sockets import DeadlineAcceptor

        acceptor = DeadlineAcceptor(self._listener)
        # kept for elastic scale-up (reference: autoscaling scheduler hook)
        self._sock = sock
        self._env = env
        self._acceptor = acceptor
        self._slots_per_worker = slots_per_worker
        # default: fixed-size pool (scale-up is an explicit opt-in via
        # max_workers > num_workers, mirroring how the reference only scales
        # when the runtime honors the scheduler's autoscaling request)
        self.max_workers = max_workers if max_workers is not None else num_workers
        self._next_worker_id = num_workers
        self.workers: Dict[str, WorkerProcess] = {}
        for i in range(num_workers):
            wid = f"worker-{i}"
            wenv = dict(env)
            if i < device_workers:
                # device LEASE: this worker gets device capability instead of
                # the pool default "off" — on single-chip hosts the chip
                # belongs to at most one process, so the lease count is an
                # explicit opt-in (reference contrast: every flotilla worker
                # runs the full engine, daft/runners/flotilla.py:112-154).
                # The mode is FIXED at spawn (subprocess env); requesting
                # device workers while the driver is configured "off" means
                # "auto" — a lease to a host-only worker would be a no-op for
                # the process lifetime.
                if device_mode is None:
                    from ..config import execution_config

                    device_mode = execution_config().device_mode
                wenv["DAFT_TPU_DEVICE"] = device_mode \
                    if device_mode != "off" else "auto"
            self.workers[wid] = WorkerProcess(wid, acceptor, sock,
                                              slots_per_worker, env=wenv)
        # ---- dispatcher state (single thread owns scheduler + worker I/O) ----
        from .scheduler import Scheduler

        self._pool_lock = threading.RLock()
        self._sched = Scheduler({w.worker_id: w.slots
                                 for w in self.workers.values() if w.alive})
        self._runs: Dict[str, _StageRun] = {}
        self._task_route: Dict[str, _StageRun] = {}
        self._incoming: deque = deque()
        self._stage_seq = 0
        self._digest_seen: Dict[str, int] = {}
        self._dispatcher: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._closed = False
        # ---- liveness monitor + elastic respawn knobs -----------------------
        hb = self._hb_interval
        # a worker silent for this long is DEAD (default ~= 3 missed beats,
        # floored so a worker busy importing jax on its first task is never
        # declared dead by an aggressive beat interval); 0/heartbeats-off
        # disables the timeout (EOF and exit-code detection still apply)
        self._hb_timeout = env_float("DAFT_TPU_HEARTBEAT_TIMEOUT_S",
                                     max(3 * hb, 6.0))
        if hb <= 0:
            self._hb_timeout = 0.0
        # elastic respawn: replace up to this many dead workers over the
        # pool's lifetime (0 = off), spaced by a doubling backoff so a
        # crash-looping environment can't hot-spin spawns
        self._respawn_cap = env_int("DAFT_TPU_WORKER_RESPAWN", 0, lo=0)
        self._respawn_attempts = 0
        self._respawn_backoff = 0.5
        self._respawn_next_t = 0.0
        # replacements still owed (one per death, so N deaths in one pass
        # respawn N workers, budget allowing — a boolean would coalesce them)
        self._pending_respawns = 0
        # spawn-env of each dead worker awaiting replacement (device leases
        # must survive respawn; FIFO pairs deaths with replacements)
        self._respawn_envs: deque = deque()
        # death ledger: worker_id -> {ts, reason} (dashboard dead-worker
        # marking); _death_events drains into synthetic heartbeats
        self.dead_workers: Dict[str, dict] = {}
        self._death_events: deque = deque()
        # cancellation requests from client threads (ServeFuture.cancel):
        # the DISPATCHER performs the actual _fail_run/drop_stream on its
        # next pass — the scheduler has no lock of its own, so only the
        # dispatcher thread may mutate it
        self._cancel_requests: set = set()
        # recovery notes that found no traced run active when the death was
        # detected (a worker can die BETWEEN stages — the EOF surfaces on the
        # next dispatch pass): drained into the next traced run so EXPLAIN
        # ANALYZE still renders the failure its recovery responded to
        self._unattributed_recovery: List[tuple] = []
        # idle-pool liveness: the dispatcher's idle loop runs a low-rate
        # liveness check (see _idle_liveness_tick), so a worker that dies
        # while NO stage is dispatching is still detected within one
        # heartbeat timeout instead of on the next dispatch pass. Start the
        # dispatcher at construction — lazily-on-first-run_tasks would leave
        # an idle pool blind until its first query.
        self._idle_check_t = 0.0
        with self._pool_lock:
            self._ensure_dispatcher()

    def scale_up(self, n: int = 1,
                 env: Optional[Dict[str, str]] = None) -> List[str]:
        """Spawn up to n extra workers (bounded by max_workers over ALIVE
        workers, so crashed workers free headroom); returns the new worker
        ids. Spawn failures are non-fatal — the pool keeps serving with what
        it has. The local realization of the reference's autoscaling request
        path (default.rs get_autoscaling_request -> runtime scale-up)."""
        added = []
        while n > 0 and sum(1 for w in self.workers.values()
                            if w.alive) < self.max_workers:
            wid = f"worker-{self._next_worker_id}"
            self._next_worker_id += 1
            try:
                self.workers[wid] = WorkerProcess(
                    wid, self._acceptor, self._sock,
                    self._slots_per_worker,
                    env=env if env is not None else self._env)
            except Exception:  # lint: ignore[broad-except] -- a failed spawn (resource limits,
                # exactly when demand spikes) must not abort the stage the
                # existing pool can still run
                break
            added.append(wid)
            n -= 1
        return added

    def run_tasks(self, tasks: List[SubPlanTask], stage_id: str = "",
                  trace=None) -> Dict[str, TaskResult]:
        """Drive one stage of tasks to completion (concurrent-caller safe).

        When `trace` (a distributed.trace.QueryTrace) is given, every task is
        stamped with the query's trace context at dispatch (trace id + parent
        span id, the otlp.py scheme) and asked to collect stats; finished
        tasks are recorded into the trace with driver-side queue-wait/dispatch
        timing joined to the worker-side execution record.
        """
        now = time.time()
        for t in tasks:
            if stage_id and not t.stage_id:
                t.stage_id = stage_id
            if trace is not None:
                t.collect_stats = True
                t.trace_id = trace.trace_id
                t.parent_span_id = trace.root_span_id
            t.submitted_at = now
        with self._pool_lock:
            if self._closed:
                raise RuntimeError("worker pool is shut down")
            self._stage_seq += 1
            key = f"{stage_id or 'stage'}#{self._stage_seq}"
            run = _StageRun(key, tasks, stage_id or "stage", trace)
            self._incoming.append(run)
            self._ensure_dispatcher()
        self._wake.set()
        # the calling thread's cancellation token (serving ServeFuture.cancel
        # installs one; bare runner threads have none): checked while waiting
        # so a cancelled query's stage stops consuming the pool — its pending
        # stream is dropped (best-effort Scheduler.drop_stream; tasks already
        # on workers finish and their results are discarded)
        from ..cancellation import QueryCancelled, cancel_event

        cancel_ev = cancel_event()
        while not run.done.wait(timeout=0.5):
            if cancel_ev is not None and cancel_ev.is_set():
                self._cancel_run(run)
            with self._pool_lock:
                alive = (self._dispatcher is not None
                         and self._dispatcher.is_alive())
            if not alive and not run.done.is_set():
                raise RuntimeError("worker pool dispatcher died")
        if run.error_kind == "cancelled":
            raise QueryCancelled(run.error or "query cancelled")
        if run.error is not None:
            # re-raise recoverable failure classes TYPED so the planner's
            # recovery loop can regenerate lost shuffle maps (worker.py
            # _classify_error is the other end of this contract)
            if run.error_kind == "shuffle_data_lost" and run.error_data:
                from .shuffle import ShuffleDataLost

                raise ShuffleDataLost(
                    run.error_data.get("shuffle_id", ""),
                    run.error_data.get("map_ids", ()), run.error)
            if run.error_kind == "shuffle_peer_unreachable" and run.error_data:
                from .shuffle import ShufflePeerUnreachable

                raise ShufflePeerUnreachable(
                    run.error_data.get("shuffle_id", ""), run.error)
            raise RuntimeError(run.error)
        if trace is not None:
            trace.note_placement(run.stage_id, run.placement_stats)
        return dict(run.results)

    # ---- dispatcher ---------------------------------------------------------------
    def _ensure_dispatcher(self) -> None:
        """Start the dispatcher lazily (pool lock held by caller)."""
        if self._dispatcher is None or not self._dispatcher.is_alive():
            self._dispatcher = threading.Thread(
                target=self._dispatch_loop, daemon=True, name="daft-dispatch")
            self._dispatcher.start()

    def _dispatch_loop(self) -> None:
        import traceback as _tb

        try:
            while True:
                with self._pool_lock:
                    if self._closed:
                        return
                    has_work = bool(self._runs or self._incoming)
                if not has_work:
                    self._wake.wait(0.05)
                    self._wake.clear()
                    self._idle_liveness_tick()
                    continue
                self._dispatch_pass()
        except Exception as e:  # noqa: BLE001 — a dispatcher crash must fail callers loudly
            err = (f"pool dispatcher crashed: {type(e).__name__}: {e}\n"
                   f"{_tb.format_exc()}")
            with self._pool_lock:
                runs = list(self._runs.values()) + list(self._incoming)
                self._runs.clear()
                self._incoming.clear()
                self._task_route.clear()
            for r in runs:
                r.fail(err)

    def _register_incoming(self) -> None:
        while True:
            with self._pool_lock:
                if not self._incoming:
                    return
                run = self._incoming.popleft()
            # seed residency digests from the latest heartbeats so this
            # stage's FIRST scheduling pass is already cache-affinity aware
            for w in list(self.workers.values()):
                if w.alive and w.failed_reason is None:
                    w.pump()
                    if self._digest_seen.get(w.worker_id) != w.digest_seq:
                        self._sched.update_residency(w.worker_id, w.last_digest)
                        self._digest_seen[w.worker_id] = w.digest_seq
            # sync scheduler membership with the pool (workers added by an
            # external scale_up() between stages must become schedulable)
            known = {s.worker_id for s in self._sched.snapshots()}
            for w in self.workers.values():
                if w.alive and w.failed_reason is None \
                        and w.worker_id not in known:
                    self._sched.add_worker(w.worker_id, w.slots)
            run.stats_before = self._sched.placement_stats()
            self._runs[run.key] = run
            if run.trace is not None and self._unattributed_recovery:
                # deaths detected while no traced run was active land on the
                # next traced run's report (see _note_worker_death)
                for key, n in self._unattributed_recovery:
                    run.trace.note_recovery(key, n)
                self._unattributed_recovery.clear()
            for t in run.tasks.values():
                self._task_route[t.task_id] = run
                self._sched.submit(t, stream_key=run.key)

    def _requeue_elsewhere(self, w: WorkerProcess, task: SubPlanTask,
                           run: _StageRun) -> None:
        clone = SubPlanTask(
            task_id=task.task_id, plan_blob=task.plan_blob,
            strategy=task.strategy, priority=task.priority,
            excluded_workers=task.excluded_workers + (w.worker_id,),
            stage_id=task.stage_id, trace_id=task.trace_id,
            parent_span_id=task.parent_span_id,
            collect_stats=task.collect_stats,
            # keep the FIRST submit time: a retry's queue wait includes
            # the failed attempt's scheduling delay
            submitted_at=task.submitted_at,
            rfingerprint=task.rfingerprint)
        run.tasks[task.task_id] = clone
        run.running.pop(task.task_id, None)
        run.speculated.discard(task.task_id)
        run.dup_worker.pop(task.task_id, None)
        registry().inc("tasks_requeued_total")
        self._sched.submit(clone, stream_key=run.key)

    def _finish_run(self, run: _StageRun) -> None:
        now = self._sched.placement_stats()
        run.placement_stats = {
            k: now.get(k, 0) - run.stats_before.get(k, 0) for k in now}
        with self._pool_lock:
            self._runs.pop(run.key, None)
            for tid in run.expected:
                self._task_route.pop(tid, None)
        run.done.set()

    def _cancel_run(self, run: _StageRun) -> None:
        """Best-effort mid-stage cancellation (ServeFuture.cancel while the
        stage runs), called from the CLIENT thread: park a request for the
        dispatcher, which drops the run's pending stream and fails it on its
        next pass — in-flight tasks complete on their workers and their late
        results are dropped by the routing table. The scheduler is only ever
        touched by the dispatcher thread (it has no lock; a client-side
        drop_stream racing the dispatcher's own _fail_run corrupted the
        stream rotation)."""
        with self._pool_lock:
            if run.key in self._runs:
                self._cancel_requests.add(run.key)
        self._wake.set()

    def _fail_run(self, run: _StageRun, error: str, kind: str = "",
                  data: Optional[dict] = None) -> None:
        self._sched.drop_stream(run.key)
        with self._pool_lock:
            self._runs.pop(run.key, None)
            for tid in run.expected:
                self._task_route.pop(tid, None)
        run.fail(error, kind, data)

    def _dispatch_pass(self) -> None:
        sched = self._sched
        self._register_incoming()
        # client-thread cancellations parked by _cancel_run: performed here
        # so every scheduler mutation stays on this thread
        with self._pool_lock:
            cancelled = [self._runs[k] for k in self._cancel_requests
                         if k in self._runs]
            self._cancel_requests.clear()
        for run in cancelled:
            self._fail_run(run, "query cancelled", kind="cancelled")
        # elastic scale-up: when queued demand exceeds capacity by the
        # autoscaling threshold, grow the pool toward max_workers — ONE
        # worker per dispatch pass, so result polling of busy workers is
        # never starved behind a burst of blocking spawns
        if sched.needs_autoscaling():
            for wid in self.scale_up(1):
                sched.add_worker(wid, self._slots_per_worker)
        assignments = sched.schedule()
        for task, wid in assignments:
            w = self.workers.get(wid)
            run = self._task_route.get(task.task_id)
            if w is None or run is None:
                # worker vanished between snapshot and submit, or the run
                # was failed/abandoned: give the slot back
                sched.task_finished(wid)
                continue
            try:
                w.submit(task)
            except (BrokenPipeError, OSError):
                w.inflight.pop(task.task_id, None)
                sched.remove_worker(wid)
                self._requeue_elsewhere(w, task, run)
                continue
            now = time.time()
            if (task.task_id in run.running
                    or task.task_id in run.results):
                # second concurrent attempt = the speculative copy
                run.dup_worker[task.task_id] = wid
            else:
                run.running[task.task_id] = (wid, now)
                run.dispatched_at.setdefault(task.task_id, now)
        progressed = bool(assignments)
        for w in list(self.workers.values()):
            res = w.poll(timeout=0.005)
            # heartbeats may have arrived during the poll: refresh this
            # worker's residency digest for the next scheduling pass —
            # but only when it actually changed (seq check), not a dict
            # copy per worker per 5ms dispatch iteration
            if self._digest_seen.get(w.worker_id) != w.digest_seq:
                sched.update_residency(w.worker_id, w.last_digest)
                self._digest_seen[w.worker_id] = w.digest_seq
            if res is not None:
                progressed = True
                sched.task_finished(res.worker_id)
                run = self._task_route.get(res.task_id)
                if run is not None:
                    self._route_result(run, res)
            # ---- liveness monitor: ACT on missing heartbeats ----------------
            # the poll above just drained whatever the connection held, so a
            # stale last_beat here is real silence, not an undrained buffer.
            # A SIGSTOP'd/hung worker never EOFs and never exits — the beat
            # timeout is the only detector that catches it.
            if w.alive and w.failed_reason is None:
                if w.conn_dead:
                    w.mark_failed("connection closed")
                elif (self._hb_timeout > 0
                        and time.time() - w.last_beat > self._hb_timeout):
                    w.mark_failed(
                        f"no heartbeat for {self._hb_timeout:.1f}s "
                        f"(interval {self._hb_interval:.1f}s)")
            if not w.alive or w.failed_reason is not None:
                # worker died: re-queue its tasks elsewhere and DROP the
                # entry (leaving it would leak its fd and pay a poll
                # error every loop; scale_up counts alive workers so the
                # slot frees for a replacement)
                if self._note_worker_death(w):
                    progressed = True
                if not any(ww.alive and ww.failed_reason is None
                           for ww in self.workers.values()):
                    # last worker gone: an immediate respawn (cap allowing)
                    # is the only alternative to failing every run
                    self._maybe_respawn(force=True)
                    if not self.workers:
                        for run in list(self._runs.values()):
                            self._fail_run(run, "all workers died")
                        return
        self._maybe_speculate()
        if self._pending_respawns > 0:
            self._maybe_respawn()
        respawn_pending = (self._pending_respawns > 0
                           and self._respawn_attempts < self._respawn_cap)
        if not progressed and sched.pending_count() and not respawn_pending \
                and not any(w.inflight for w in self.workers.values()):
            # nothing running, nothing newly assignable -> unschedulable;
            # fail every run that still has unfinished tasks
            for run in list(self._runs.values()):
                if len(run.results) < len(run.expected):
                    self._fail_run(
                        run, f"{sched.pending_count()} tasks unschedulable "
                             f"(no eligible workers)")

    def _idle_liveness_tick(self) -> None:
        """Low-rate liveness check for an IDLE pool (dispatcher thread, no
        dispatch pass running). The _dispatch_pass liveness monitor only runs
        while stages are in flight, so without this an idle pool never
        noticed a kill -9'd worker — the dashboard's dead-worker marking and
        the respawn path both waited for the next query. Same detection as
        the dispatch-pass block: pump() first so a stale last_beat is real
        silence, then connection-EOF / process-exit / heartbeat-timeout."""
        if self._hb_timeout > 0:
            interval = max(min(self._hb_timeout / 3.0, 2.0), 0.1)
        else:
            interval = 1.0  # EOF/exit detection still applies with beats off
        now = time.time()
        if now - self._idle_check_t < interval:
            return
        self._idle_check_t = now
        for w in list(self.workers.values()):
            if not (w.alive and w.failed_reason is None):
                self._note_worker_death(w)
                continue
            w.pump()
            if w.conn_dead:
                w.mark_failed("connection closed")
            elif (self._hb_timeout > 0
                    and time.time() - w.last_beat > self._hb_timeout):
                w.mark_failed(
                    f"no heartbeat for {self._hb_timeout:.1f}s "
                    f"(interval {self._hb_interval:.1f}s)")
            if not w.alive or w.failed_reason is not None:
                self._note_worker_death(w)
        if self._pending_respawns > 0:
            self._maybe_respawn()

    def _note_worker_death(self, w: WorkerProcess) -> bool:
        """Handle one dead worker: counters + death ledger, requeue its
        in-flight tasks (excluding it), drop it from scheduler and pool, and
        arm a respawn. Returns True when tasks were requeued (dispatch
        progress)."""
        now = time.time()
        rc = w._proc.poll()
        reason = w.failed_reason or f"process exited (code {rc})"
        registry().inc("worker_failures_total")
        from ..observability import flight as _flight

        frec = _flight.recorder()
        if frec is not None:
            frec.note_worker_death(w.worker_id, reason)
        self.dead_workers[w.worker_id] = {"ts": now, "reason": reason}
        self._death_events.append(
            {"worker_id": w.worker_id, "ts": now, "reason": reason})
        self._sched.remove_worker(w.worker_id)
        progressed = False
        requeued = 0
        if w.inflight:
            for t in list(w.inflight.values()):
                run = self._task_route.get(t.task_id)
                if run is None or t.task_id in run.results:
                    continue  # result already won elsewhere
                self._requeue_elsewhere(w, t, run)
                requeued += 1
                if run.trace is not None:
                    run.trace.note_recovery("tasks_requeued", 1)
            w.inflight.clear()
            progressed = requeued > 0
        # the failure is an event of the QUERIES sharing this pool: note it
        # once per distinct active trace so EXPLAIN ANALYZE can render
        # "recovery: N worker failures, ..."
        seen_traces = set()
        for run in self._runs.values():
            tr = run.trace
            if tr is not None and id(tr) not in seen_traces:
                seen_traces.add(id(tr))
                tr.note_recovery("worker_failures", 1)
        if not seen_traces:
            # no traced run was active at detection time (death between
            # stages): park the note for the next traced run's report
            self._unattributed_recovery.append(("worker_failures", 1))
        w.stop()
        self.workers.pop(w.worker_id, None)
        if self._respawn_cap > 0:
            self._pending_respawns += 1
            # the replacement inherits the dead worker's spawn env (device
            # lease above all) so recovery restores capability, not just count
            self._respawn_envs.append(dict(w.spawn_env))
        return progressed

    def _maybe_respawn(self, force: bool = False) -> None:
        """Spawn a replacement for a dead worker, bounded by
        DAFT_TPU_WORKER_RESPAWN total attempts with a doubling backoff
        between them (force=True skips the backoff wait — the all-workers-
        dead case where the alternative is failing every run)."""
        if self._respawn_cap <= 0 or self._respawn_attempts >= self._respawn_cap:
            self._pending_respawns = 0
            self._respawn_envs.clear()
            return
        alive = sum(1 for w in self.workers.values()
                    if w.alive and w.failed_reason is None)
        if alive >= self.max_workers:
            # capacity already restored — queue-pressure autoscaling raced
            # the respawn for the dead worker's freed headroom. The pool is
            # whole again; a no-op scale_up here would silently burn a
            # capped attempt.
            self._pending_respawns = 0
            self._respawn_envs.clear()
            return
        now = time.time()
        if not force and now < self._respawn_next_t:
            return  # backoff window; retried on a later pass
        self._respawn_attempts += 1
        self._respawn_next_t = now + self._respawn_backoff
        self._respawn_backoff = min(self._respawn_backoff * 2, 30.0)
        env = self._respawn_envs.popleft() if self._respawn_envs else None
        added = self.scale_up(1, env=env)
        for wid in added:
            self._sched.add_worker(wid, self._slots_per_worker)
            registry().inc("worker_respawns_total")
        if added:
            self._pending_respawns = max(0, self._pending_respawns - 1)

    def _route_result(self, run: _StageRun, res: TaskResult) -> None:
        if res.task_id in run.results:
            return  # speculative loser (or duplicate retry): first result won
        if res.error is not None:
            # a failed SPECULATIVE copy must never fail a stage the original
            # attempt can still win — speculation may only mask stragglers,
            # not introduce failures
            if (res.task_id in run.speculated
                    and res.worker_id == run.dup_worker.get(res.task_id)):
                run.dup_worker.pop(res.task_id, None)
                run.speculated.discard(res.task_id)
                return
            self._fail_run(
                run,
                f"task {res.task_id} failed on {res.worker_id}:\n{res.error_tb}",
                res.error_kind, res.error_data)
            return
        run.results[res.task_id] = res
        run.running.pop(res.task_id, None)
        run.completed_times.append(res.exec_seconds or 0.0)
        if (res.task_id in run.speculated
                and res.worker_id == run.dup_worker.get(res.task_id)):
            registry().inc("sched_speculative_wins")
        if run.trace is not None and res.task_id in run.tasks:
            run.trace.record_task(run.tasks[res.task_id], res,
                                  run.dispatched_at.get(res.task_id, 0.0))
        if len(run.results) == len(run.expected):
            self._finish_run(run)

    def _maybe_speculate(self) -> None:
        """Duplicate-dispatch running stragglers (first result wins). A task
        qualifies once its stage has >= 2 completed tasks and its elapsed
        time exceeds straggler_threshold() x the completed median and the
        DAFT_TPU_SPECULATIVE_MIN_S floor (default 0.25s — trivial tasks are
        never worth a duplicate)."""
        if not env_bool("DAFT_TPU_SPECULATIVE", True):
            return
        import statistics

        from .trace import straggler_threshold

        floor = env_float("DAFT_TPU_SPECULATIVE_MIN_S", 0.25)
        k = straggler_threshold()
        now = time.time()
        for run in list(self._runs.values()):
            if len(run.completed_times) < 2 or not run.running:
                continue
            med = statistics.median(run.completed_times)
            cutoff = max(k * med, floor)
            for task_id, (wid, t0) in list(run.running.items()):
                if task_id in run.speculated or task_id in run.results:
                    continue
                if now - t0 <= cutoff:
                    continue
                task = run.tasks.get(task_id)
                if task is None:
                    continue
                excluded = task.excluded_workers + (wid,)
                if not any(w.alive and w.worker_id not in excluded
                           for w in self.workers.values()):
                    continue  # nowhere else to run the duplicate
                clone = SubPlanTask(
                    task_id=task.task_id, plan_blob=task.plan_blob,
                    strategy=task.strategy, priority=task.priority,
                    excluded_workers=excluded,
                    stage_id=task.stage_id, trace_id=task.trace_id,
                    parent_span_id=task.parent_span_id,
                    collect_stats=task.collect_stats,
                    submitted_at=task.submitted_at,
                    rfingerprint=task.rfingerprint)
                run.speculated.add(task_id)
                self._sched.submit(clone, stream_key=run.key)
                registry().inc("sched_speculative_dispatches")

    def drain_heartbeats(self, preserve_deaths: bool = False) -> List[dict]:
        """Collect heartbeats received from every live worker since the last
        drain (the runner forwards them to subscribers / the dashboard).
        Task results encountered while draining are preserved for poll().
        Worker deaths since the last drain are appended as synthetic final
        beats carrying dead=True + the failure reason, so the dashboard MARKS
        dead workers instead of silently letting them go stale.
        preserve_deaths=True empties only the worker pipes and leaves queued
        death events for the next full drain — the runner's start-of-query
        DISCARD drain must not swallow the one-shot dead=True records the
        dashboard's latch depends on."""
        out: List[dict] = []
        # snapshot: the dispatcher thread pops dead workers / inserts
        # respawns concurrently with this (runner-thread) drain
        for w in list(self.workers.values()):
            out.extend(w.drain_heartbeats())
        if preserve_deaths:
            out.sort(key=lambda h: h.get("ts", 0.0))
            return out
        while self._death_events:
            try:
                ev = self._death_events.popleft()
            except IndexError:
                break
            out.append({"worker_id": ev["worker_id"], "ts": ev["ts"],
                        "recv_ts": ev["ts"], "busy_slots": 0,
                        "total_slots": 0, "tasks_completed": 0,
                        "tasks_failed": 0, "rss_bytes": 0, "uptime_s": 0.0,
                        "dead": True, "death_reason": ev["reason"]})
        out.sort(key=lambda h: h.get("ts", 0.0))
        return out

    def latest_heartbeats(self) -> Dict[str, dict]:
        """worker_id -> most recent heartbeat payload for every live worker
        that has ever beaten. The runner's end-of-query window filter can
        come up empty for a query faster than one heartbeat period; these
        survive that filter so the dashboard still sees the whole pool."""
        return {w.worker_id: w.last_hb
                for w in list(self.workers.values()) if w.last_hb is not None}

    def shutdown(self) -> None:
        with self._pool_lock:
            self._closed = True
            dispatcher = self._dispatcher
            runs = list(self._runs.values()) + list(self._incoming)
            self._runs.clear()
            self._incoming.clear()
            self._task_route.clear()
        self._wake.set()
        if dispatcher is not None and dispatcher.is_alive():
            dispatcher.join(timeout=2.0)
        for r in runs:
            r.fail("worker pool shut down mid-stage")
        for w in self.workers.values():
            w.stop()
        self.workers.clear()
        try:
            self._listener.close()
        except OSError:
            pass


if __name__ == "__main__":
    main(sys.argv[1:])
