"""Worker subprocess entry point (kept separate from worker.py so that
``python -m`` does not re-execute a module already imported by the package).
"""

import sys

if __name__ == "__main__":
    from daft_tpu.distributed.worker import main

    main(sys.argv[1:])
