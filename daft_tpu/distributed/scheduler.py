"""Task scheduler: priority heap + worker snapshots + scheduling strategies.

Reference parity: src/daft-distributed/src/scheduling/scheduler/default.rs:9 —
pending tasks in a priority heap; each scheduling pass snapshots worker
capacity and assigns: Spread -> worker with most available slots (default.rs:48),
WorkerAffinity soft -> preferred worker if it has a slot else spread, hard ->
only that worker. Pure logic, no IO — hermetically unit-tested with mock
workers exactly like the reference (scheduling/scheduler/mod.rs:257-298).
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .task import Spread, SubPlanTask, WorkerAffinity


@dataclass
class WorkerSnapshot:
    worker_id: str
    total_slots: int
    active_tasks: int = 0

    @property
    def available_slots(self) -> int:
        return max(self.total_slots - self.active_tasks, 0)


class Scheduler:
    """Assigns pending tasks to workers with capacity.

    Usage: submit() tasks, then schedule() to drain as many as capacity allows
    (schedule() itself marks assigned slots busy); call task_finished() as
    results arrive to free slots.
    """

    def __init__(self, workers: Dict[str, int]):
        import os

        self._workers: Dict[str, WorkerSnapshot] = {
            wid: WorkerSnapshot(wid, slots) for wid, slots in workers.items()
        }
        self._heap: List[Tuple[int, int, SubPlanTask]] = []
        self._seq = itertools.count()
        try:
            self._autoscaling_threshold = float(
                os.environ.get("DAFT_TPU_AUTOSCALING_THRESHOLD", 1.25))
        except ValueError:
            self._autoscaling_threshold = 1.25

    # ---- worker lifecycle ----------------------------------------------------
    def add_worker(self, worker_id: str, slots: int) -> None:
        self._workers[worker_id] = WorkerSnapshot(worker_id, slots)

    def remove_worker(self, worker_id: str) -> None:
        self._workers.pop(worker_id, None)

    def task_finished(self, worker_id: str) -> None:
        w = self._workers.get(worker_id)
        if w is not None and w.active_tasks > 0:
            w.active_tasks -= 1

    def snapshots(self) -> List[WorkerSnapshot]:
        return list(self._workers.values())

    # ---- scheduling ----------------------------------------------------------
    def submit(self, task: SubPlanTask) -> None:
        # lower priority value = scheduled first (matches reference heap order)
        heapq.heappush(self._heap, (task.priority, next(self._seq), task))

    def pending_count(self) -> int:
        return len(self._heap)

    def needs_autoscaling(self) -> bool:
        """True when pending demand exceeds total capacity by the threshold
        factor (DAFT_TPU_AUTOSCALING_THRESHOLD, default 1.25 — reference:
        default.rs needs_autoscaling). Cheap: called every dispatch loop."""
        if not self._heap:
            return False
        if not self._workers:
            return True
        total_capacity = sum(w.total_slots for w in self._workers.values())
        return len(self._heap) > total_capacity * self._autoscaling_threshold

    def get_autoscaling_request(self) -> Optional[List[SubPlanTask]]:
        """Pending tasks justifying scale-up, or None (reference:
        default.rs get_autoscaling_request)."""
        if not self.needs_autoscaling():
            return None
        return [t for _p, _s, t in self._heap]

    def schedule(self) -> List[Tuple[SubPlanTask, str]]:
        """Assign as many pending tasks as current capacity allows.

        Tasks whose strategy cannot be satisfied right now (hard affinity to a
        busy/absent worker, every eligible worker full) stay pending.
        """
        assigned: List[Tuple[SubPlanTask, str]] = []
        skipped: List[Tuple[int, int, SubPlanTask]] = []
        while self._heap:
            prio, seq, task = heapq.heappop(self._heap)
            wid = self._pick_worker(task)
            if wid is None:
                skipped.append((prio, seq, task))
                continue
            self._workers[wid].active_tasks += 1
            assigned.append((task, wid))
        for item in skipped:
            heapq.heappush(self._heap, item)
        return assigned

    def _pick_worker(self, task: SubPlanTask) -> Optional[str]:
        strategy = task.strategy
        eligible = [w for w in self._workers.values()
                    if w.worker_id not in task.excluded_workers]
        if isinstance(strategy, WorkerAffinity):
            pref = self._workers.get(strategy.worker_id)
            pref_ok = (pref is not None and pref.available_slots > 0
                       and pref.worker_id not in task.excluded_workers)
            if pref_ok:
                return pref.worker_id
            if strategy.hard:
                return None
        free = [w for w in eligible if w.available_slots > 0]
        if not free:
            return None
        # Spread: most available slots; stable tiebreak by id for determinism
        return max(free, key=lambda w: (w.available_slots, w.worker_id)).worker_id
