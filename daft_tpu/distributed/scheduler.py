"""Task scheduler: priority heap + worker snapshots + scheduling strategies.

Reference parity: src/daft-distributed/src/scheduling/scheduler/default.rs:9 —
pending tasks in a priority heap; each scheduling pass snapshots worker
capacity and assigns: Spread -> worker with most available slots (default.rs:48),
WorkerAffinity soft -> preferred worker if it has a slot else spread, hard ->
only that worker. Pure logic, no IO — hermetically unit-tested with mock
workers exactly like the reference (scheduling/scheduler/mod.rs:257-298).

Cache-affinity extension (Delay Scheduling / Sparrow lineage): each
WorkerSnapshot carries the worker's RESIDENCY DIGEST — the stable slot keys of
device planes its HBM holds, published in heartbeats
(device/residency.py digest()). A task whose ``rfingerprint``
(distributed/affinity.py) intersects a free worker's digest is steered there,
scored by estimated transfer-bytes-avoided minus a load penalty, so repeat
sub-plans stick to the worker that already paid their uploads. The policy is
SOFT: nothing resident, a saturated preferred worker, or a losing score all
degrade to the plain spread pick — no task ever waits for locality.

Fair multi-stream extension (serving tier): pending tasks live in PER-STREAM
heaps keyed by ``stream_key`` (the WorkerPool passes one key per concurrent
run_tasks call, i.e. per query stage). With one stream the drain is the
original one-pass greedy order; with several, schedule() deals tasks
round-robin ONE per stream per rotation, so a query arriving behind a
100-task stage still gets its first task dispatched after at most one
rotation — admission fairness extends through to worker slots. The rotation
start advances across calls so no stream is permanently first. The scheduler
is NOT internally locked: one owner (the pool's dispatcher thread) drives it.
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..observability.metrics import registry
from ..utils.env import env_float, env_int
from .task import Spread, SubPlanTask, WorkerAffinity


@dataclass
class WorkerSnapshot:
    worker_id: str
    total_slots: int
    active_tasks: int = 0
    # latest heartbeat residency digest: stable slot key -> device bytes held
    resident: Dict[int, int] = field(default_factory=dict)

    @property
    def available_slots(self) -> int:
        return max(self.total_slots - self.active_tasks, 0)


class Scheduler:
    """Assigns pending tasks to workers with capacity.

    Usage: submit() tasks, then schedule() to drain as many as capacity allows
    (schedule() itself marks assigned slots busy); call task_finished() as
    results arrive to free slots. update_residency() feeds worker heartbeat
    digests in between passes.
    """

    def __init__(self, workers: Dict[str, int]):
        self._workers: Dict[str, WorkerSnapshot] = {
            wid: WorkerSnapshot(wid, slots) for wid, slots in workers.items()
        }
        # stream_key -> pending heap (insertion-ordered for the rotation)
        self._queues: "Dict[str, List[Tuple[int, int, SubPlanTask]]]" = {}
        self._stream_order: List[str] = []
        self._rr_pos = 0
        self._seq = itertools.count()
        self._autoscaling_threshold = env_float(
            "DAFT_TPU_AUTOSCALING_THRESHOLD", 1.25)
        # load penalty per active task when scoring affinity candidates: an
        # affinity pick must beat spread by more than this many bytes per unit
        # of load, or locality is not worth queueing behind a busy worker
        self._affinity_penalty_bytes = env_int(
            "DAFT_TPU_AFFINITY_PENALTY_BYTES", 8 * 1024 * 1024)
        # per-scheduler placement totals (the pool snapshots these into the
        # query trace; the same increments go to the process registry)
        self._stats = {"affinity_hits": 0, "affinity_misses": 0,
                       "bytes_avoided": 0, "affinity_skips": 0}

    # ---- worker lifecycle ----------------------------------------------------
    def add_worker(self, worker_id: str, slots: int) -> None:
        self._workers[worker_id] = WorkerSnapshot(worker_id, slots)

    def remove_worker(self, worker_id: str) -> None:
        self._workers.pop(worker_id, None)

    def task_finished(self, worker_id: str) -> None:
        w = self._workers.get(worker_id)
        if w is not None and w.active_tasks > 0:
            w.active_tasks -= 1

    def update_residency(self, worker_id: str, digest) -> None:
        """Install a worker's latest heartbeat residency digest (iterable of
        (stable_slot_key, bytes) pairs, or a dict)."""
        w = self._workers.get(worker_id)
        if w is None:
            return
        w.resident = dict(digest) if digest else {}

    def snapshots(self) -> List[WorkerSnapshot]:
        return list(self._workers.values())

    def placement_stats(self) -> Dict[str, int]:
        """Affinity placement totals since construction (one Scheduler serves
        one stage, so these are per-stage numbers for the query trace)."""
        return dict(self._stats)

    # ---- scheduling ----------------------------------------------------------
    def submit(self, task: SubPlanTask, stream_key: Optional[str] = None) -> None:
        # lower priority value = scheduled first (matches reference heap
        # order) WITHIN a stream; streams deal round-robin against each other
        key = stream_key if stream_key is not None else (task.stage_id or "")
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = []
            self._stream_order.append(key)
        heapq.heappush(q, (task.priority, next(self._seq), task))

    def drop_stream(self, stream_key: str) -> int:
        """Purge a stream's pending tasks (its stage errored/was abandoned);
        returns how many were dropped. In-flight tasks are unaffected."""
        q = self._queues.pop(stream_key, None)
        if stream_key in self._stream_order:
            self._stream_order.remove(stream_key)
        return len(q) if q else 0

    def pending_count(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _pending_tasks(self) -> List[SubPlanTask]:
        return [t for q in self._queues.values() for _p, _s, t in q]

    def needs_autoscaling(self) -> bool:
        """True when pending demand exceeds total capacity by the threshold
        factor (DAFT_TPU_AUTOSCALING_THRESHOLD, default 1.25 — reference:
        default.rs needs_autoscaling). Cheap: called every dispatch loop."""
        pending = self.pending_count()
        if not pending:
            return False
        if not self._workers:
            return True
        total_capacity = sum(w.total_slots for w in self._workers.values())
        return pending > total_capacity * self._autoscaling_threshold

    def get_autoscaling_request(self) -> Optional[List[SubPlanTask]]:
        """Pending tasks justifying scale-up, or None (reference:
        default.rs get_autoscaling_request)."""
        if not self.needs_autoscaling():
            return None
        return self._pending_tasks()

    def schedule(self) -> List[Tuple[SubPlanTask, str]]:
        """Assign as many pending tasks as current capacity allows.

        Tasks whose strategy cannot be satisfied right now (hard affinity to a
        busy/absent worker, every eligible worker full) stay pending. A
        hard-affinity task that finds its preferred worker full marks that
        worker in a per-pass skip set: later heap entries bound to the same
        worker are re-queued without an eligibility scan instead of spinning
        the heap head-of-line (counted in sched_affinity_skips).

        With several pending streams, assignments rotate one-per-stream so
        concurrent queries share worker capacity fairly instead of FIFO
        head-of-line (see the module docstring).
        """
        live = [k for k in self._stream_order if self._queues.get(k)]
        blocked_prefs: Set[str] = set()
        if len(live) <= 1:
            # single stream: the original one-pass greedy drain
            return (self._drain_stream(live[0], blocked_prefs, limit=0)
                    if live else [])
        # rotate the starting stream across calls so no stream is always first
        start = self._rr_pos % len(live)
        self._rr_pos += 1
        order = live[start:] + live[:start]
        assigned: List[Tuple[SubPlanTask, str]] = []
        progress = True
        while progress:
            progress = False
            for key in order:
                got = self._drain_stream(key, blocked_prefs, limit=1)
                if got:
                    assigned.extend(got)
                    progress = True
        return assigned

    def _drain_stream(self, key: str, blocked_prefs: Set[str],
                      limit: int) -> List[Tuple[SubPlanTask, str]]:
        """Pop schedulable tasks from one stream's heap (at most `limit`;
        0 = until capacity runs out). Unschedulable entries are re-queued,
        preserving the head-of-line skip-set behavior within the stream."""
        heap = self._queues.get(key)
        if not heap:
            return []
        assigned: List[Tuple[SubPlanTask, str]] = []
        skipped: List[Tuple[int, int, SubPlanTask]] = []
        while heap:
            prio, seq, task = heapq.heappop(heap)
            strategy = task.strategy
            if (isinstance(strategy, WorkerAffinity) and strategy.hard
                    and strategy.worker_id in blocked_prefs):
                self._stats["affinity_skips"] += 1
                registry().inc("sched_affinity_skips")
                skipped.append((prio, seq, task))
                continue
            wid = self._pick_worker(task)
            if wid is None:
                if isinstance(strategy, WorkerAffinity) and strategy.hard:
                    # only a genuinely FULL preferred worker poisons the skip
                    # set: a task whose pref is merely excluded (requeue) or
                    # absent must not starve siblings the worker could serve
                    pref = self._workers.get(strategy.worker_id)
                    if (pref is not None and pref.available_slots == 0
                            and strategy.worker_id not in task.excluded_workers):
                        blocked_prefs.add(strategy.worker_id)
                skipped.append((prio, seq, task))
                continue
            self._workers[wid].active_tasks += 1
            assigned.append((task, wid))
            if limit and len(assigned) >= limit:
                break
        for item in skipped:
            heapq.heappush(heap, item)
        if not heap:
            self._queues.pop(key, None)
            if key in self._stream_order:
                self._stream_order.remove(key)
        return assigned

    def _pick_worker(self, task: SubPlanTask) -> Optional[str]:
        strategy = task.strategy
        eligible = [w for w in self._workers.values()
                    if w.worker_id not in task.excluded_workers]
        if isinstance(strategy, WorkerAffinity):
            pref = self._workers.get(strategy.worker_id)
            pref_ok = (pref is not None and pref.available_slots > 0
                       and pref.worker_id not in task.excluded_workers)
            if pref_ok:
                return pref.worker_id
            if strategy.hard:
                return None
        free = [w for w in eligible if w.available_slots > 0]
        if not free:
            return None
        wid = self._pick_resident(task, free, eligible)
        if wid is not None:
            return wid
        # Spread: most available slots; stable tiebreak by id for determinism
        return max(free, key=lambda w: (w.available_slots, w.worker_id)).worker_id

    def _pick_resident(self, task: SubPlanTask, free: List[WorkerSnapshot],
                       eligible: List[WorkerSnapshot]) -> Optional[str]:
        """Cache-affinity pick: the free worker with the best
        (bytes-avoided − load·penalty) score, when positive. Returns None to
        fall through to spread (also recording a miss when the task's planes
        sit only on workers with no free slot — locality lost to saturation)."""
        fp = task.rfingerprint
        if not fp:
            return None
        best: Optional[WorkerSnapshot] = None
        best_score = 0
        best_avoided = 0
        for w in free:
            avoided = self._overlap_bytes(w, fp)
            if avoided <= 0:
                continue
            score = avoided - self._affinity_penalty_bytes * w.active_tasks
            if best is None or (score, w.available_slots, w.worker_id) > \
                    (best_score, best.available_slots, best.worker_id):
                best, best_score, best_avoided = w, score, avoided
        if best is not None and best_score > 0:
            self._stats["affinity_hits"] += 1
            self._stats["bytes_avoided"] += best_avoided
            registry().inc("sched_affinity_hits")
            registry().inc("sched_bytes_avoided", best_avoided)
            return best.worker_id
        if any(w.available_slots == 0 and self._overlap_bytes(w, fp) > 0
               for w in eligible):
            self._stats["affinity_misses"] += 1
            registry().inc("sched_affinity_misses")
        return None

    @staticmethod
    def _overlap_bytes(w: WorkerSnapshot, fp) -> int:
        if not w.resident:
            return 0
        # bytes the worker actually holds for the slots this task would probe
        return sum(w.resident.get(k, 0) for k, _est in fp)
