"""Central JAX configuration for the engine.

Import this module before any device work. Enables 64-bit mode: a data engine's
aggregation semantics (int64 sums, float64 means) require x64; compute-heavy kernels
opt into bf16/f32 explicitly where precision allows (SURVEY.md §7 MXU notes).
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)


def get_jax():
    return jax
