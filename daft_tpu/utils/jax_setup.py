"""Central JAX configuration for the engine.

Import this module before any device work. Enables 64-bit mode: a data engine's
aggregation semantics (int64 sums, float64 means) require x64; compute-heavy kernels
opt into bf16/f32 explicitly where precision allows (SURVEY.md §7 MXU notes).
"""

from __future__ import annotations

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: stage programs (scan-of-matmul groupbys etc.)
# can take tens of seconds to compile over a tunneled device; caching across
# processes makes every run after the first start warm.
_cache_dir = os.environ.get(
    "DAFT_TPU_COMPILE_CACHE", os.path.expanduser("~/.cache/daft_tpu_xla"))
if _cache_dir:
    try:
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # lint: ignore[broad-except] -- persistent compile cache
        pass  # is an optimization; failing to set it up must not break jax init


def get_jax():
    return jax
