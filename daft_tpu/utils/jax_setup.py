"""Central JAX configuration for the engine.

Import this module before any device work. Enables 64-bit mode: a data engine's
aggregation semantics (int64 sums, float64 means) require x64; compute-heavy kernels
opt into bf16/f32 explicitly where precision allows (SURVEY.md §7 MXU notes).
"""

from __future__ import annotations

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: stage programs (scan-of-matmul groupbys etc.)
# can take tens of seconds to compile over a tunneled device — on real silicon
# the tests_tpu tier measured ~2 min/test of pure recompiles without it.
# DAFT_TPU_COMPILE_CACHE_DIR is the canonical knob (DAFT_TPU_COMPILE_CACHE is
# honored as the legacy spelling); "0"/"off"/"" disables.


def compile_cache_dir() -> str:
    """Resolved persistent-compile-cache directory ("" = disabled)."""
    raw = os.environ.get("DAFT_TPU_COMPILE_CACHE_DIR")
    if raw is None:
        raw = os.environ.get("DAFT_TPU_COMPILE_CACHE")
    if raw is None:
        raw = os.path.expanduser("~/.cache/daft_tpu_xla")
    if raw.strip().lower() in ("", "0", "off", "false", "no"):
        return ""
    return os.path.expanduser(raw)


_cache_dir = compile_cache_dir()
if _cache_dir:
    try:
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # lint: ignore[broad-except] -- persistent compile cache
        pass  # is an optimization; failing to set it up must not break jax init


def get_jax():
    return jax
