"""Defensive environment-variable parsing.

One site for the parse-or-default idiom the distributed knobs repeat
(heartbeat cadence, respawn caps, retry budgets): a malformed value NEVER
raises — production knobs must degrade to their defaults, not crash a worker
or driver at import/spawn time. `lo` clamps the floor where a knob has one
(slot counts >= 1, retry budgets >= 0).

These four helpers are the engine's single blessed idiom for reading knobs:
the lint rule ``env-discipline`` (daft_tpu/tools/lint/) rejects raw
``int(os.environ...)`` / ``float(os.environ...)`` parses anywhere else, so a
new knob can't reintroduce the crash-on-typo behavior this module exists to
kill.
"""

from __future__ import annotations

import os
from typing import Optional


def env_int(name: str, default: int, lo: Optional[int] = None) -> int:
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        v = default
    return v if lo is None else max(v, lo)


def env_float(name: str, default: float, lo: Optional[float] = None) -> float:
    try:
        v = float(os.environ.get(name, default))
    except ValueError:
        v = default
    return v if lo is None else max(v, lo)


def env_str(name: str, default: str = "") -> str:
    """String knob (mode selectors, file paths). Trivial today, but the one
    spelling keeps every knob read greppable and lintable at a single call
    shape."""
    return os.environ.get(name, default)


_FALSY = ("0", "off", "false", "no", "")


def env_bool(name: str, default: bool) -> bool:
    """Flag knob. Absent -> default; set -> anything but a falsy spelling
    ("0"/"off"/"false"/"no"/empty, case-insensitive) counts as on — matching
    the DAFT_TPU_SPECULATIVE=0 convention the distributed tier established."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSY
