"""Defensive environment-variable parsing.

One site for the parse-or-default idiom the distributed knobs repeat
(heartbeat cadence, respawn caps, retry budgets): a malformed value NEVER
raises — production knobs must degrade to their defaults, not crash a worker
or driver at import/spawn time. `lo` clamps the floor where a knob has one
(slot counts >= 1, retry budgets >= 0).
"""

from __future__ import annotations

import os
from typing import Optional


def env_int(name: str, default: int, lo: Optional[int] = None) -> int:
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        v = default
    return v if lo is None else max(v, lo)


def env_float(name: str, default: float, lo: Optional[float] = None) -> float:
    try:
        v = float(os.environ.get(name, default))
    except ValueError:
        v = default
    return v if lo is None else max(v, lo)
