"""Socket helpers shared by the distributed and UDF worker pools."""

from __future__ import annotations

import queue
import threading


class DeadlineAcceptor:
    """listener.accept() with a wall-clock deadline and no lost connections.

    multiprocessing's accept() performs the HMAC auth handshake on the
    accepted socket in BLOCKING mode, so a stranger that connects and sends
    nothing would hang a naive caller forever. Accepts run in background
    threads feeding a queue; accept(timeout) polls the queue. A completed
    handshake is NEVER discarded (late arrivals are picked up by the next
    call), and a stalled stranger only pins one of the bounded accept threads
    — the caller keeps its deadline and reports an error instead of hanging.
    """

    _MAX_THREADS = 8

    def __init__(self, listener):
        self.listener = listener
        self._q: queue.Queue = queue.Queue()
        self._inflight = 0
        self._lock = threading.Lock()

    def _spawn(self) -> None:
        with self._lock:
            if self._inflight >= self._MAX_THREADS:
                return
            self._inflight += 1

        def run():
            try:
                conn = self.listener.accept()
                self._q.put(conn)
            except Exception as e:  # noqa: BLE001 — surfaced to the caller
                self._q.put(e)
            finally:
                with self._lock:
                    self._inflight -= 1

        threading.Thread(target=run, daemon=True).start()

    def accept(self, timeout_s: float):
        """Returns a connection, None on timeout, or raises accept's error
        (e.g. AuthenticationError for a wrong-key client)."""
        with self._lock:
            need = self._inflight == 0
        if need:
            self._spawn()
        try:
            item = self._q.get(timeout=timeout_s)
        except queue.Empty:
            # current accept may be pinned by a stalled handshake; allow one
            # more concurrent accept so real workers still get through
            self._spawn()
            return None
        if isinstance(item, Exception):
            raise item
        return item
