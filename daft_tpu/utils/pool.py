"""Shared compute thread pool (reference: common/runtime compute runtime —
numpy/arrow kernels release the GIL, so morsel parallelism works on threads)."""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from .env import env_int

_POOL: Optional[ThreadPoolExecutor] = None


def compute_pool() -> ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        workers = env_int("DAFT_TPU_NUM_THREADS", os.cpu_count() or 4, lo=1)
        _POOL = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="daft-compute")
    return _POOL


def pool_map(fn, items):
    """Map over items in the pool; falls back to serial for 0/1 items."""
    items = list(items)
    if len(items) <= 1:
        return [fn(x) for x in items]
    return list(compute_pool().map(fn, items))
