"""Mesh-sharded relational compute: the multi-chip execution path.

TPU-native replacement for the reference's distributed data movement
(src/daft-distributed "Flotilla" + src/daft-shuffles Arrow-Flight shuffle):
within a mesh, repartition/aggregation exchange rides ICI via XLA collectives
(psum / all_to_all) inside ONE jit program instead of host-side shuffle services;
cross-host DCN exchange reuses the same primitives through jax.distributed.

Layout: rows are data-parallel sharded along the 'dp' mesh axis (each device
owns a contiguous row shard, padded with validity=False rows). Ungrouped
aggregation = local masked reduce + psum. Grouped aggregation = local
segment-reduce into a fixed-width group-hash table + psum — the device
equivalent of partial→final two-phase aggregation.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import jax_setup  # noqa: F401
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..expressions.expressions import AggExpr, Expression
from ..ops import device_eval as dev
from ..ops.stage import _decompose_agg, pad_bucket
from ..schema import Schema


def default_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def shard_columns(mesh: Mesh, columns: Dict[str, Tuple[np.ndarray, np.ndarray]],
                  n: int, axis: str = "dp") -> Dict[str, Tuple[jax.Array, jax.Array]]:
    """Pad host columns to a multiple of the mesh size and place them row-sharded."""
    n_dev = mesh.shape[axis]
    per = pad_bucket(max((n + n_dev - 1) // n_dev, 1))
    total = per * n_dev
    sharding = NamedSharding(mesh, P(axis))
    out = {}
    for name, (vals, valid) in columns.items():
        if len(vals) < total:
            pad = total - len(vals)
            vals = np.concatenate([vals, np.zeros(pad, dtype=vals.dtype)])
            valid = np.concatenate([valid, np.zeros(pad, dtype=bool)])
        out[name] = (jax.device_put(vals, sharding), jax.device_put(valid, sharding))
    return out


def sharded_filter_agg_step(mesh: Mesh, schema: Schema, predicate: Optional[Expression],
                            aggs: Sequence[Tuple[str, AggExpr]], axis: str = "dp") -> Callable:
    """Build a pjit'd distributed filter+ungrouped-agg step.

    Returns fn(cols) -> {(name, partial_op): (value, valid)} with replicated outputs.
    With row-sharded inputs, XLA lowers the reductions to per-shard partials plus a
    psum over ICI — no explicit collective code needed beyond the sharding contract.
    """
    pred_fn = dev.build_device_expr(predicate, schema) if predicate is not None else None
    agg_specs = []
    for name, agg in aggs:
        child_fn = dev.build_device_expr(agg.child, schema)
        count_all = agg.op == "count" and agg.params.get("mode", "valid") == "all"
        agg_specs.append((name, agg.op, count_all, child_fn))

    def step(cols):
        if pred_fn is not None:
            pv, pm = pred_fn(cols)
            keep = pv.astype(bool) & pm
        else:
            any_col = next(iter(cols.values()))
            keep = jnp.ones(jnp.shape(any_col[0]), dtype=bool)
        out = {}
        for name, op, count_all, child_fn in agg_specs:
            v, m = child_fn(cols)
            m = dev._broadcast_valid(v, m) & keep
            if count_all:
                m = dev._broadcast_valid(v, keep)
            for partial_op in _decompose_agg(op):
                val, ok = dev.device_agg(partial_op, v, m)
                out[(name, partial_op)] = (val, ok)
        return out

    replicated = NamedSharding(mesh, P())
    return jax.jit(step, out_shardings=replicated)


def sharded_grouped_agg_step(mesh: Mesh, schema: Schema, key_col: str,
                             agg_col: str, agg_op: str, num_buckets: int,
                             axis: str = "dp") -> Callable:
    """Distributed groupby-aggregate over integer group keys via shard_map.

    Each device segment-reduces its row shard into a fixed-width bucket table
    (key hashed to [0, num_buckets)), then a psum over the mesh axis combines
    partial tables — two-phase aggregation where the 'shuffle' is one ICI
    collective. Returns fn(keys, values, valid) -> (bucket_sums, bucket_counts),
    both replicated [num_buckets] arrays.
    """
    from jax.experimental.shard_map import shard_map

    def local(keys, values, valid):
        b = (keys % num_buckets).astype(jnp.int32)
        vals = jnp.where(valid, values.astype(jnp.float64), 0.0)
        sums = jax.ops.segment_sum(vals, b, num_segments=num_buckets)
        counts = jax.ops.segment_sum(valid.astype(jnp.int64), b, num_segments=num_buckets)
        sums = jax.lax.psum(sums, axis)
        counts = jax.lax.psum(counts, axis)
        return sums, counts

    mapped = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(), P()),
    )
    return jax.jit(mapped)
