"""Mesh-sharded relational compute: the multi-chip execution path.

TPU-native replacement for the reference's distributed data movement
(reference: src/daft-distributed "Flotilla" + src/daft-shuffles Arrow-Flight
shuffle): within a mesh, repartition/aggregation exchange rides ICI via XLA
collectives (psum / all_gather) inside ONE jit program instead of host-side
shuffle services; cross-host DCN exchange reuses the same primitives through
jax.distributed.

Layout: rows are data-parallel sharded along the 'dp' mesh axis (each device
owns a contiguous row shard, padded with validity=False rows). Ungrouped
aggregation = local masked reduce + psum. Grouped aggregation = local
sort/unique + segment-reduce into a fixed-capacity group table, then an
all_gather table merge — an EXACT two-phase groupby whose 'shuffle' is one ICI
collective. Capacity is static (XLA needs static shapes); exceeding it is
reported via an overflow flag so the host can re-run with a larger table.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import threading

import numpy as np

from ..utils import jax_setup  # noqa: F401
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..expressions.expressions import AggExpr, Expression
from ..ops import device_eval as dev
from ..ops.stage import _decompose_agg, pad_bucket
from ..schema import Schema

# Sentinel key for invalid / padding rows: sorts after every real key.
_KEY_SENTINEL = np.iinfo(np.int64).max


_MESH_CACHE: Dict[Tuple[int, str], Mesh] = {}
# kernels are built from concurrent serving/executor threads (PR 8 discipline)
_CACHE_LOCK = threading.Lock()


def default_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    """1-D data-parallel mesh over the first `n_devices` local devices.

    Raises when more devices are requested than exist: silently building a
    smaller mesh from the slice (the pre-r7 behavior) made a forced
    `mesh_devices=N` config lie about its own width — callers that can
    degrade (the executor tier gate) must decide that themselves and count it
    (counters.mesh_unavailable_fallbacks)."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(
            f"default_mesh: {n} devices requested but only {len(devs)} "
            f"available (jax.devices())")
    key = (n, axis)
    with _CACHE_LOCK:
        cached = _MESH_CACHE.get(key)
        if cached is None:
            cached = _MESH_CACHE[key] = Mesh(np.array(devs[:n]), (axis,))
    return cached


def shard_columns(mesh: Mesh, columns: Dict[str, Tuple[np.ndarray, np.ndarray]],
                  n: int, axis: str = "dp") -> Dict[str, Tuple[jax.Array, jax.Array]]:
    """Pad host columns to a multiple of the mesh size and place them row-sharded."""
    n_dev = mesh.shape[axis]
    per = pad_bucket(max((n + n_dev - 1) // n_dev, 1))
    total = per * n_dev
    sharding = NamedSharding(mesh, P(axis))
    out = {}
    for name, (vals, valid) in columns.items():
        if len(vals) < total:
            pad = total - len(vals)
            vals = np.concatenate([vals, np.zeros(pad, dtype=vals.dtype)])
            valid = np.concatenate([valid, np.zeros(pad, dtype=bool)])
        out[name] = (jax.device_put(vals, sharding), jax.device_put(valid, sharding))
    return out


def shard_row_mask(mesh: Mesh, n: int, axis: str = "dp") -> jax.Array:
    """Row-sharded bool mask marking real rows (False on shard padding).

    Needed by count(mode=all): null values count, padding rows must not.
    """
    n_dev = mesh.shape[axis]
    per = pad_bucket(max((n + n_dev - 1) // n_dev, 1))
    total = per * n_dev
    mask = np.zeros(total, dtype=bool)
    mask[:n] = True
    return jax.device_put(mask, NamedSharding(mesh, P(axis)))


def sharded_filter_agg_step(mesh: Mesh, schema: Schema, predicate: Optional[Expression],
                            aggs: Sequence[Tuple[str, AggExpr]], axis: str = "dp") -> Callable:
    """Build a pjit'd distributed filter+ungrouped-agg step.

    Returns fn(cols, row_mask) -> {(name, partial_op): (value, valid)} with
    replicated outputs; row_mask (see shard_row_mask) marks real rows so shard
    padding never reaches an aggregate — count(mode=all) counts nulls, not padding.
    With row-sharded inputs, XLA lowers the reductions to per-shard partials plus a
    psum over ICI — no explicit collective code needed beyond the sharding contract.
    """
    pred_fn = dev.build_device_expr(predicate, schema) if predicate is not None else None
    agg_specs = []
    for name, agg in aggs:
        child_fn = dev.build_device_expr(agg.child, schema)
        count_all = agg.op == "count" and agg.params.get("mode", "valid") == "all"
        agg_specs.append((name, agg.op, count_all, child_fn))

    def step(cols, row_mask):
        if pred_fn is not None:
            pv, pm = pred_fn(cols)
            keep = pv.astype(bool) & pm & row_mask
        else:
            keep = row_mask
        out = {}
        for name, op, count_all, child_fn in agg_specs:
            v, m = child_fn(cols)
            m = dev._broadcast_valid(v, m) & keep
            if count_all:
                m = dev._broadcast_valid(v, keep)
            for partial_op in _decompose_agg(op):
                val, ok = dev.device_agg(partial_op, v, m)
                out[(name, partial_op)] = (val, ok)
        return out

    replicated = NamedSharding(mesh, P())
    return jax.jit(step, out_shardings=replicated)


# canonical masked segment reduce shared with the single-chip grouped stage
_segment_reduce = dev.segment_reduce


def _merge_op(op: str) -> str:
    """Reduce op used when merging per-shard partial tables."""
    return {"count": "sum", "sum": "sum", "min": "min", "max": "max"}[op]


_STEP_CACHE: Dict[tuple, Callable] = {}


def sharded_groupby_step(mesh: Mesh, agg_ops: Sequence[str], capacity: int,
                         axis: str = "dp") -> Callable:
    """EXACT distributed groupby-aggregate over int64 group keys.

    Each device: sort/unique its row shard's keys into a fixed-capacity group
    table (jnp.unique with static size) and segment-reduce values per group.
    Merge: all_gather the per-shard tables over the mesh axis and re-reduce —
    two-phase aggregation where the shuffle is one ICI collective. No hashing,
    no collisions: real keys are carried through both phases.

    agg_ops: per value-column ops from {sum, count, min, max, mean}.
    capacity: max distinct keys (static; XLA shape). Exceeding it sets the
    returned overflow flag (host should retry with a larger capacity).

    Returns fn(keys, key_valid, *[(values, valid) flattened]) ->
      (group_keys[capacity], group_valid[capacity], overflow_scalar,
       results: tuple of per-column (values[capacity], valid[capacity])).
    Rows with invalid keys (nulls / shard padding) are excluded.
    """
    ops = list(agg_ops)
    # memoize the compiled step: repeated groupbys at the same (mesh, ops,
    # capacity) reuse one jitted multi-device program instead of rebuilding a
    # fresh closure that can never cache-hit (Mesh is hashable by value)
    cache_key = (mesh, tuple(ops), capacity, axis)
    cached = _STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached
    cap1 = capacity + 1  # one extra slot so the sentinel never evicts a real key

    def _true_unique_count(sorted_keys: jnp.ndarray) -> jnp.ndarray:
        """Number of distinct non-sentinel keys in an ascending-sorted array."""
        real = sorted_keys != _KEY_SENTINEL
        first = jnp.concatenate([
            jnp.ones((1,), dtype=bool),
            sorted_keys[1:] != sorted_keys[:-1],
        ])
        return jnp.sum(first & real)

    def local(keys, key_valid, *flat):
        cols = [(flat[2 * i], flat[2 * i + 1]) for i in range(len(ops))]
        k = jnp.where(key_valid, keys.astype(jnp.int64), _KEY_SENTINEL)
        sorted_k = jnp.sort(k)
        local_nu = _true_unique_count(sorted_k)
        uk = jnp.unique(k, size=cap1, fill_value=_KEY_SENTINEL)
        seg = jnp.searchsorted(uk, k)

        # per-column partial tables; a "count" partial is always included so the
        # merge phase can null out groups whose values are all-null
        col_partials: List[List[str]] = []
        partial_tables = []
        for (v, m), op in zip(cols, ops):
            mask = dev._broadcast_valid(k, m) & key_valid
            partials = list(_decompose_agg(op))
            if "count" not in partials:
                partials.append("count")
            col_partials.append(partials)
            for partial in partials:
                partial_tables.append(_segment_reduce(partial, v, mask, seg, cap1))

        # merge phase: gather every shard's table, re-group by real key
        all_k = jax.lax.all_gather(uk, axis).reshape(-1)
        gathered = [jax.lax.all_gather(t, axis).reshape(-1) for t in partial_tables]
        fuk = jnp.unique(all_k, size=cap1, fill_value=_KEY_SENTINEL)
        fseg = jnp.searchsorted(fuk, all_k)

        idx = 0
        results = []
        src_valid = all_k != _KEY_SENTINEL
        for op, partials in zip(ops, col_partials):
            merged = {}
            for partial in partials:
                t = gathered[idx]
                idx += 1
                merged[partial] = _segment_reduce(
                    _merge_op(partial), t, src_valid, fseg, cap1
                )
            cnt = merged["count"]
            if op == "mean":
                val = merged["sum"] / jnp.maximum(cnt, 1)
                ok = cnt > 0
            elif op == "count":
                val = cnt
                ok = jnp.ones_like(cnt, dtype=bool)
            else:
                val = merged[op]
                ok = cnt > 0
            results.append((val[:capacity], ok[:capacity]))

        total_nu = _true_unique_count(jnp.sort(all_k))
        overflow = (
            jax.lax.pmax(local_nu, axis) > capacity
        ) | (total_nu > capacity)
        group_keys = fuk[:capacity]
        group_valid = group_keys != _KEY_SENTINEL
        return group_keys, group_valid, overflow, tuple(results)

    in_specs = tuple([P(axis), P(axis)] + [P(axis)] * (2 * len(ops)))
    out_specs = (P(), P(), P(), tuple((P(), P()) for _ in ops))
    step = jax.jit(_shard_map(local, mesh, in_specs, out_specs))
    with _CACHE_LOCK:
        _STEP_CACHE[cache_key] = step
    return step


def _shard_map(local, mesh: Mesh, in_specs, out_specs):
    """shard_map across the jax spelling drift (check_vma vs check_rep)."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(local, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:  # pre-0.8 jax spells it check_rep
        return shard_map(local, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def sharded_gather_step(mesh: Mesh, n_cols: int, axis: str = "dp") -> Callable:
    """Build the mesh join-feed probe: fact rows row-sharded, dim planes
    REPLICATED on every device — the probe is a purely local gather (the
    'broadcast probe' of the two-tier design; no collective until the reduce).

    Returns fn(idx, row_mask, *[(vals, valid) x n_cols flattened]) ->
    tuple of (gathered_vals, gathered_valid) pairs, row-sharded like `idx`.
    idx: int64 fact->dim row indices, < 0 = no dim match (inner-join
    semantics: the row's gathered validity goes False). Output planes feed
    straight into sharded_groupby_step / sharded_filter_agg-style reduces.
    """
    cache_key = ("gather", mesh, n_cols, axis)
    cached = _STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached

    def local(idx, row_mask, *flat):
        keep = row_mask & (idx >= 0)
        safe = jnp.maximum(idx, 0)
        out = []
        for i in range(n_cols):
            v, m = flat[2 * i], flat[2 * i + 1]
            out.append((v[safe], m[safe] & keep))
        return tuple(out)

    in_specs = tuple([P(axis), P(axis)] + [P()] * (2 * n_cols))
    out_specs = tuple((P(axis), P(axis)) for _ in range(n_cols))
    step = jax.jit(_shard_map(local, mesh, in_specs, out_specs))
    with _CACHE_LOCK:
        _STEP_CACHE[cache_key] = step
    return step


def sharded_join_agg_step(mesh: Mesh, specs: Sequence[Tuple[str, int]],
                          n_dims: int, axis: str = "dp") -> Callable:
    """Sharded star-join fact feed + ungrouped aggregate in ONE program.

    Fact rows are row-sharded along the mesh axis; each dim's value plane is
    replicated (broadcast) so the probe is a local gather through the dim's
    sharded fact->dim index plane; the reduce is one ICI collective per
    partial (psum for sum/count — exact for int64 — pmin/pmax for extremes).

    specs: per aggregate (op, src) with op in {sum, count, mean, min, max}
    and src = dim index whose replicated value plane the aggregate reads
    (gathered to fact rows), or -1 for a fact-local row-sharded plane.

    Returns fn(row_mask, idx_planes_tuple, *[(vals, valid) per spec]) ->
    {(i, partial_op): (value, valid)} replicated — combine across batches on
    the host with ops.stage._combine_partials.
    """
    specs = tuple((op, int(src)) for op, src in specs)
    cache_key = ("joinagg", mesh, specs, n_dims, axis)
    cached = _STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached

    def local(row_mask, idxs, *flat):
        keep = row_mask
        for ix in idxs:
            keep = keep & (ix >= 0)
        safe = [jnp.maximum(ix, 0) for ix in idxs]
        out = {}
        for i, (op, src) in enumerate(specs):
            v, m = flat[2 * i], flat[2 * i + 1]
            if src >= 0:
                v, m = v[safe[src]], m[safe[src]]
            mask = m & keep
            cnt = jax.lax.psum(jnp.sum(mask), axis)
            for partial in _decompose_agg(op):
                if partial == "count":
                    out[(i, "count")] = (cnt, jnp.asarray(True))
                elif partial == "sum":
                    pv, _ok = dev.device_agg("sum", v, mask)
                    out[(i, "sum")] = (jax.lax.psum(pv, axis), cnt > 0)
                else:  # min / max
                    big = dev._extreme(v.dtype, partial == "min")
                    masked = jnp.where(mask, v, big)
                    red = jnp.min(masked) if partial == "min" else jnp.max(masked)
                    coll = jax.lax.pmin if partial == "min" else jax.lax.pmax
                    out[(i, partial)] = (coll(red, axis), cnt > 0)
        return out

    in_specs = (
        P(axis),
        tuple(P(axis) for _ in range(n_dims)),
    ) + tuple(P(axis) if specs[i // 2][1] < 0 else P()
              for i in range(2 * len(specs)))
    out_specs = {(i, partial): (P(), P())
                 for i, (op, _src) in enumerate(specs)
                 for partial in _decompose_agg(op)}
    step = jax.jit(_shard_map(local, mesh, in_specs, out_specs))
    with _CACHE_LOCK:
        _STEP_CACHE[cache_key] = step
    return step


def _joined_cols(schema, col_specs, idxs, flat):
    """Shared join-feed plumbing for the fused mesh join programs: assemble
    the joined column dict (fact planes row-sharded as-is; dim planes
    replicated, gathered through the per-dim sharded index planes) plus the
    all-dims-matched inner-join mask. idx < 0 = no dim match; a gathered
    column's validity additionally drops rows whose OWN dim missed (a row can
    match dim A but miss dim B — its A-columns stay valid until join_ok
    kills the row)."""
    join_ok = None
    for ix in idxs:
        ok = ix >= 0
        join_ok = ok if join_ok is None else (join_ok & ok)
    cols: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = {}
    for i, (name, src) in enumerate(col_specs):
        v, m = flat[2 * i], flat[2 * i + 1]
        if src >= 0:
            ix = idxs[src]
            safe = jnp.maximum(ix, 0)
            v, m = v[safe], m[safe] & (ix >= 0)
        cols[name] = (v, m)
    return cols, join_ok


def _keep_mask(pred_fn, cols, join_ok, row_mask):
    keep = row_mask if join_ok is None else (row_mask & join_ok)
    if pred_fn is not None:
        pv, pm = pred_fn(cols)
        keep = keep & pv.astype(bool) & pm
    return keep


def sharded_join_ungrouped_stage_step(mesh: Mesh, schema: Schema,
                                      predicate: Optional[Expression],
                                      col_specs: Sequence[Tuple[str, int]],
                                      agg_specs: Sequence[Tuple[str, str, bool, Expression]],
                                      n_dims: int, axis: str = "dp") -> Callable:
    """Fused mesh star-join fact feed, ungrouped: gather + predicate +
    partial aggregates + ICI reduce in ONE program.

    Fact rows (and the per-dim fact->dim index planes) are row-sharded along
    the mesh axis; dim value planes are replicated so the probe is a purely
    local gather (the broadcast-probe half of SURVEY §7's two-tier shuffle
    mapping). The cross-shard exchange is one psum/pmin/pmax per partial —
    exact for int64 sums, which accumulate in int64 end to end.

    col_specs: (column name, src) with src = the dim index plane the column
    gathers through, or -1 for a fact-local row-sharded plane.
    agg_specs: (name, op, count_all, child expression) per aggregate.
    Returns fn(row_mask, idxs_tuple, *flat) -> {(name, partial): (val, ok)}
    replicated — combined across batches with ops.stage._combine_partials.
    """
    pred_fn = dev.build_device_expr(predicate, schema) \
        if predicate is not None else None
    built = [(name, op, count_all, dev.build_device_expr(child, schema))
             for name, op, count_all, child in agg_specs]
    col_specs = tuple((str(n), int(s)) for n, s in col_specs)

    def local(row_mask, idxs, *flat):
        cols, join_ok = _joined_cols(schema, col_specs, idxs, flat)
        keep = _keep_mask(pred_fn, cols, join_ok, row_mask)
        out = {}
        for name, op, count_all, child_fn in built:
            v, m = child_fn(cols)
            mask = dev._broadcast_valid(v, m) & keep
            if count_all:
                mask = dev._broadcast_valid(v, keep)
            cnt = jax.lax.psum(jnp.sum(mask), axis)
            for partial in _decompose_agg(op):
                if partial == "count":
                    out[(name, "count")] = (cnt, jnp.asarray(True))
                elif partial == "sum":
                    pv, _ok = dev.device_agg("sum", v, mask)
                    out[(name, "sum")] = (jax.lax.psum(pv, axis), cnt > 0)
                else:  # min / max
                    big = dev._extreme(v.dtype, partial == "min")
                    masked = jnp.where(mask, v, big)
                    red = jnp.min(masked) if partial == "min" \
                        else jnp.max(masked)
                    coll = jax.lax.pmin if partial == "min" else jax.lax.pmax
                    out[(name, partial)] = (coll(red, axis), cnt > 0)
        return out

    in_specs = (
        P(axis),
        tuple(P(axis) for _ in range(n_dims)),
    ) + tuple(P(axis) if col_specs[i // 2][1] < 0 else P()
              for i in range(2 * len(col_specs)))
    out_specs = {(name, partial): (P(), P())
                 for name, op, _ca, _child in built
                 for partial in _decompose_agg(op)}
    return jax.jit(_shard_map(local, mesh, in_specs, out_specs))


def sharded_join_grouped_stage_step(mesh: Mesh, schema: Schema,
                                    predicate: Optional[Expression],
                                    col_specs: Sequence[Tuple[str, int]],
                                    slot_specs: Sequence[Tuple[str, bool, Expression]],
                                    capacity: int, n_dims: int,
                                    axis: str = "dp") -> Callable:
    """Fused mesh star-join fact feed, grouped: gather + predicate + DENSE
    group-code segment reduce + ICI table merge in ONE program.

    Group codes come from the host factorize of the JOINED keys (dense
    first-occurrence ids, exact true group count — any key dtype), so the
    per-shard reduce is a straight segment_sum/min/max into a [capacity+1]
    table (no sort, no unique, no searchsorted: dense codes ARE the segment
    ids) and the cross-shard 'shuffle' is one psum (sum/count) or pmin/pmax
    (extremes) per partial table — the ICI replacing the host repartition
    that a two-phase host groupby would pay.

    slot_specs: (partial_op, count_all, child expression) per kernel slot —
    aggregates arrive decomposed (mean -> sum+count) so per-batch tables
    merge exactly on host across the stream.
    Returns fn(codes, row_mask, idxs_tuple, *flat) ->
      (rows[cap] int64, overflow scalar, ((vals[cap], ok[cap]) per slot))
    replicated; rows = real joined rows per group (group_valid = rows > 0).
    """
    pred_fn = dev.build_device_expr(predicate, schema) \
        if predicate is not None else None
    built = [(op, count_all, dev.build_device_expr(child, schema))
             for op, count_all, child in slot_specs]
    col_specs = tuple((str(n), int(s)) for n, s in col_specs)
    cap1 = capacity + 1  # spare slot: masked/garbage codes land there

    def local(codes, row_mask, idxs, *flat):
        cols, join_ok = _joined_cols(schema, col_specs, idxs, flat)
        keep = _keep_mask(pred_fn, cols, join_ok, row_mask)
        in_range = (codes >= 0) & (codes < capacity)
        seg = jnp.where(keep & in_range, codes, capacity)
        rows = jax.lax.psum(
            _segment_reduce("count", codes, keep & in_range, seg, cap1), axis)
        overflow = jax.lax.psum(jnp.sum(keep & ~in_range), axis) > 0
        results = []
        for op, count_all, child_fn in built:
            v, m = child_fn(cols)
            mask = dev._broadcast_valid(v, keep) if count_all \
                else dev._broadcast_valid(v, m) & keep
            table = _segment_reduce(op, v, mask, seg, cap1)
            cnt = jax.lax.psum(
                _segment_reduce("count", v, mask, seg, cap1), axis)
            if op in ("sum", "count"):
                merged = jax.lax.psum(table, axis)
            else:
                coll = jax.lax.pmin if op == "min" else jax.lax.pmax
                merged = coll(table, axis)
            ok = cnt > 0 if op != "count" else jnp.ones(cap1, dtype=bool)
            results.append((merged[:capacity], ok[:capacity]))
        return rows[:capacity], overflow, tuple(results)

    in_specs = (
        P(axis),
        P(axis),
        tuple(P(axis) for _ in range(n_dims)),
    ) + tuple(P(axis) if col_specs[i // 2][1] < 0 else P()
              for i in range(2 * len(col_specs)))
    out_specs = (P(), P(), tuple((P(), P()) for _ in built))
    return jax.jit(_shard_map(local, mesh, in_specs, out_specs))


def sharded_alltoall_repartition_step(mesh: Mesh, dtypes: Sequence,
                                      axis: str = "dp") -> Callable:
    """Intra-host repartition over ICI: each shard stable-sorts its rows by
    destination, packs them into per-destination bins, and ONE
    ``jax.lax.all_to_all`` routes every bin to its owner — the in-mesh
    replacement for the host shuffle's write-files/fetch round trip when the
    'workers' are co-located mesh shards (SURVEY §7's two-tier mapping:
    ICI inside the host, DCN/host shuffle across hosts).

    dtypes: one per exchanged plane (column values and validity planes are
    both planes here). Bins are padded to the full shard size S (worst case
    one destination receives everything), so each device holds an
    [n_dev, S]-shaped scratch per plane — an input-sized copy per device.
    The path is an EXPLICIT opt-in (executor._mesh_repart_eligible requires
    a forced mesh_devices width matching the partition count), not
    cost-gated: forced tiers run forced, like every other forced tier.

    Returns fn(dest, row_mask, *planes) ->
      (counts[n_dev*n_dev] int64, tuple of exchanged planes [n_dev*n_dev, S])
    where row-block ``d * n_dev + j`` of an exchanged plane holds source
    shard j's rows destined to partition d (first counts[d*n_dev+j] rows
    real, in original stream order — stable sort + contiguous row shards
    preserve it end to end).
    """
    n_dev = int(mesh.shape[axis])
    dtypes = tuple(dtypes)

    def local(dest, row_mask, *planes):
        S = dest.shape[0]
        counts, mats = _repart_sort_pack(dest, row_mask, planes, n_dev, S)
        outs = [jax.lax.all_to_all(m, axis, split_axis=0, concat_axis=0,
                                   tiled=True) for m in mats]
        cnt_x = jax.lax.all_to_all(counts.reshape(n_dev, 1), axis,
                                   split_axis=0, concat_axis=0, tiled=True)
        return cnt_x.reshape(n_dev), tuple(outs)

    in_specs = tuple([P(axis), P(axis)] + [P(axis)] * len(dtypes))
    out_specs = (P(axis), tuple(P(axis) for _ in dtypes))
    return jax.jit(_shard_map(local, mesh, in_specs, out_specs))


def _repart_sort_pack(dest, row_mask, planes, n_dev: int, S: int):
    """Shared local half of both repartition exchanges: stable-sort this
    shard's rows by destination and scatter them into per-destination bins.
    Returns (counts[n_dev] int64, one [n_dev, S] bin matrix per plane)."""
    d = jnp.where(row_mask, dest.astype(jnp.int64), n_dev)
    order = jnp.argsort(d)  # jax argsort lowers to a stable lax.sort
    d_sorted = d[order]
    valid_sorted = d_sorted < n_dev
    counts = _segment_reduce("count", d, d < n_dev,
                             jnp.minimum(d, n_dev), n_dev + 1)[:n_dev]
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int64),
                               jnp.cumsum(counts)[:-1]])
    safe_bin = jnp.minimum(d_sorted, n_dev - 1)
    pos = jnp.arange(S, dtype=jnp.int64) - offsets[safe_bin]
    flat_idx = jnp.where(valid_sorted, safe_bin * S + pos, n_dev * S)
    mats = []
    for p in planes:
        sp = p[order]
        mat = jnp.zeros((n_dev * S,), dtype=p.dtype)
        mat = mat.at[flat_idx].set(sp, mode="drop")
        mats.append(mat.reshape(n_dev, S))
    return counts, mats


def _pack_words(mat: jnp.ndarray) -> jnp.ndarray:
    """[n_dev, S] plane of any device dtype -> [n_dev, W] uint32 words,
    bit-exact and invertible by _unpack_words: 64-bit types split into two
    words, <=32-bit types widen losslessly to one."""
    dt = mat.dtype
    if dt.itemsize == 8:
        return jax.lax.bitcast_convert_type(mat, jnp.uint32) \
            .reshape(mat.shape[0], -1)
    if dt == jnp.bool_:
        return mat.astype(jnp.uint32)
    if jnp.issubdtype(dt, jnp.floating):
        return jax.lax.bitcast_convert_type(mat.astype(jnp.float32),
                                            jnp.uint32)
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        return mat.astype(jnp.uint32)
    return jax.lax.bitcast_convert_type(mat.astype(jnp.int32), jnp.uint32)


def _unpack_words(words: jnp.ndarray, dt, S: int) -> jnp.ndarray:
    """Inverse of _pack_words: [n_dev, W] uint32 back to an [n_dev, S] dt
    plane."""
    dt = jnp.dtype(dt)
    if dt.itemsize == 8:
        pair = words.reshape(words.shape[0], S, 2)
        if jnp.issubdtype(dt, jnp.floating):
            return jax.lax.bitcast_convert_type(pair, jnp.float64)
        return jax.lax.bitcast_convert_type(pair, jnp.uint64).astype(dt)
    if dt == jnp.bool_:
        return words != 0
    if jnp.issubdtype(dt, jnp.floating):
        return jax.lax.bitcast_convert_type(words, jnp.float32).astype(dt)
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        return words.astype(dt)
    return jax.lax.bitcast_convert_type(words, jnp.int32).astype(dt)


def sharded_ring_repartition_step(mesh: Mesh, dtypes: Sequence,
                                  axis: str = "dp",
                                  interpret: bool = False) -> Callable:
    """The Pallas tier of the intra-host repartition: same contract and
    bit-identical results as sharded_alltoall_repartition_step, but the
    exchange is an IN-KERNEL ICI ring permute (ops/pallas_kernels.py
    ring_permute_bits — a pallas_call issuing per-step remote DMAs with
    send/recv semaphores) instead of a standalone jax.lax.all_to_all. The
    sort, the per-destination pack, the permute and the unpack all lower
    into ONE compiled program with ZERO separate mesh collective dispatches
    — every plane (and the counts) bitcast into a single [n_dev, W] uint32
    word buffer so the ring crosses the interconnect exactly once.

    Selected by the executor's repartition exchange under DAFT_TPU_PALLAS
    (on = engage, interpret off-silicon; auto = silicon only); a runtime
    lowering failure there latches back onto the all_to_all tier and
    replays the batch.
    """
    n_dev = int(mesh.shape[axis])
    dtypes = tuple(dtypes)

    def local(dest, row_mask, *planes):
        from ..ops.pallas_kernels import ring_permute_bits

        S = dest.shape[0]
        counts, mats = _repart_sort_pack(dest, row_mask, planes, n_dev, S)
        words = [_pack_words(m) for m in mats]
        widths = [w.shape[1] for w in words]
        words.append(_pack_words(counts.reshape(n_dev, 1)))
        buf = jnp.concatenate(words, axis=1)
        out = ring_permute_bits(buf, axis, interpret=interpret)
        outs = []
        off = 0
        for dt, w in zip(dtypes, widths):
            outs.append(_unpack_words(out[:, off:off + w], dt, S))
            off += w
        cnt_x = _unpack_words(out[:, off:off + 2], np.int64, 1)
        return cnt_x.reshape(n_dev), tuple(outs)

    in_specs = tuple([P(axis), P(axis)] + [P(axis)] * len(dtypes))
    out_specs = (P(axis), tuple(P(axis) for _ in dtypes))
    return jax.jit(_shard_map(local, mesh, in_specs, out_specs))


def groupby_host(mesh: Mesh, keys: np.ndarray, key_valid: np.ndarray,
                 value_cols: Sequence[Tuple[np.ndarray, np.ndarray]],
                 agg_ops: Sequence[str], axis: str = "dp",
                 capacity: Optional[int] = None):
    """Host driver for sharded_groupby_step: shards inputs, retries on overflow.

    Returns (group_keys np.int64[g], per-col list of (values np, valid np)) with
    only real groups (overflow resolved by doubling capacity).
    """
    n = len(keys)
    keys = keys.astype(np.int64)
    if key_valid.any() and keys[key_valid].max() == _KEY_SENTINEL:
        raise ValueError(
            f"group key {_KEY_SENTINEL} (int64 max) is reserved as the null/padding "
            "sentinel on the device groupby path"
        )
    if capacity is None:
        capacity = max(int(2 ** np.ceil(np.log2(max(16, min(n, 4096))))), 16)
    cols = {"__key__": (keys, key_valid)}
    for i, (v, m) in enumerate(value_cols):
        cols[f"__v{i}__"] = (v, m)
    sharded = shard_columns(mesh, cols, n, axis=axis)
    flat = []
    for i in range(len(value_cols)):
        dv, dm = sharded[f"__v{i}__"]
        flat += [dv, dm]
    while True:
        step = sharded_groupby_step(mesh, agg_ops, capacity, axis=axis)
        gk, gv, overflow, results = step(
            sharded["__key__"][0], sharded["__key__"][1], *flat
        )
        if bool(np.asarray(overflow)):
            capacity *= 2
            continue
        gk = np.asarray(gk)
        gv = np.asarray(gv)
        keep = gv
        out_cols = [
            (np.asarray(v)[keep], np.asarray(ok)[keep]) for v, ok in results
        ]
        return gk[keep], out_cols
