from .distributed import (
    default_mesh,
    sharded_filter_agg_step,
    sharded_grouped_agg_step,
    shard_columns,
)

__all__ = [
    "default_mesh",
    "sharded_filter_agg_step",
    "sharded_grouped_agg_step",
    "shard_columns",
]
