from .distributed import (
    default_mesh,
    groupby_host,
    sharded_filter_agg_step,
    sharded_groupby_step,
    shard_columns,
)

__all__ = [
    "default_mesh",
    "groupby_host",
    "sharded_filter_agg_step",
    "sharded_groupby_step",
    "shard_columns",
]
