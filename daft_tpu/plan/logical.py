"""Logical plan IR.

Reference parity: src/daft-logical-plan/src/logical_plan.rs:34-63 (27-op LogicalPlan
enum, one file per op under ops/) and src/daft-logical-plan/src/builder/mod.rs:61.

Design: immutable tree of nodes; each node derives its output Schema from its
children (the reference resolves/binds expressions at build time — we do the same
via Expression.to_field against the child schema). Optimizer rules rewrite the
tree bottom-up/top-down via transform hooks.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..datatype import DataType, Field
from ..expressions import AggExpr, Alias, ColumnRef, Expression
from ..schema import Schema

_plan_ids = itertools.count()


class LogicalPlan:
    """Base logical plan node. Subclasses set _schema lazily via _compute_schema."""

    def __init__(self) -> None:
        self._id = next(_plan_ids)
        self._schema_cache: Optional[Schema] = None

    # ---- structure ---------------------------------------------------------------
    def children(self) -> List["LogicalPlan"]:
        return []

    def with_children(self, children: List["LogicalPlan"]) -> "LogicalPlan":
        if children:
            raise ValueError(f"{type(self).__name__} takes no children")
        return self

    @property
    def schema(self) -> Schema:
        if self._schema_cache is None:
            self._schema_cache = self._compute_schema()
        return self._schema_cache

    def _compute_schema(self) -> Schema:
        raise NotImplementedError(type(self).__name__)

    def name(self) -> str:
        return type(self).__name__

    # ---- traversal ---------------------------------------------------------------
    def walk(self):
        yield self
        for c in self.children():
            yield from c.walk()

    def transform_up(self, fn) -> "LogicalPlan":
        """Bottom-up rewrite; fn(node) returns replacement or None to keep."""
        old = self.children()
        new = [c.transform_up(fn) for c in old]
        node = self.with_children(new) if any(a is not b for a, b in zip(new, old)) else self
        out = fn(node)
        return out if out is not None else node

    def transform_down(self, fn) -> "LogicalPlan":
        out = fn(self)
        node = out if out is not None else self
        old = node.children()
        new = [c.transform_down(fn) for c in old]
        if any(a is not b for a, b in zip(new, old)):
            node = node.with_children(new)
        return node

    # ---- display -----------------------------------------------------------------
    def display(self) -> str:
        lines: List[str] = []

        def rec(node: "LogicalPlan", depth: int) -> None:
            lines.append("  " * depth + "* " + node.describe())
            for c in node.children():
                rec(c, depth + 1)

        rec(self, 0)
        return "\n".join(lines)

    def describe(self) -> str:
        return self.name()

    def __repr__(self) -> str:
        return self.display()

    # ---- stats (filled by optimizer enrich pass; see stats.py) ---------------------
    @property
    def approx_num_rows(self) -> Optional[float]:
        return getattr(self, "_approx_num_rows", None)


# ======================================================================================
# Sources
# ======================================================================================


class InMemorySource(LogicalPlan):
    """Scan over already-materialized MicroPartitions (reference: ops/source.rs InMemory).

    `partitions` is a PartitionSet-like list of MicroPartition.
    """

    def __init__(self, schema: Schema, partitions: List[Any]):
        super().__init__()
        self._schema = schema
        self.partitions = partitions

    def _compute_schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return f"InMemorySource[{len(self.partitions)} partitions, {self._schema.short_repr()}]"


class ScanSource(LogicalPlan):
    """Scan over external storage via a ScanOperator (reference: SourceInfo::Physical).

    Pushdowns (columns/filters/limit) are attached by optimizer rules; the scan
    operator is asked for ScanTasks at physical-translate time (MaterializeScans).
    """

    def __init__(self, scan_op: Any, pushdowns: Optional[Any] = None):
        super().__init__()
        from ..io.scan import Pushdowns  # local import to avoid cycle

        self.scan_op = scan_op
        self.pushdowns = pushdowns if pushdowns is not None else Pushdowns()

    def _compute_schema(self) -> Schema:
        base = self.scan_op.schema()
        if self.pushdowns.columns is not None:
            return Schema([base[c] for c in self.pushdowns.columns])
        return base

    def describe(self) -> str:
        return f"ScanSource[{self.scan_op.name()}, pushdowns={self.pushdowns}]"


# ======================================================================================
# Row-wise ops
# ======================================================================================


class Project(LogicalPlan):
    def __init__(self, input: LogicalPlan, projection: List[Expression]):
        super().__init__()
        self.input = input
        self.projection = list(projection)

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Project(children[0], self.projection)

    def _compute_schema(self) -> Schema:
        in_schema = self.input.schema
        return Schema([e.to_field(in_schema) for e in self.projection])

    def describe(self) -> str:
        return f"Project[{', '.join(e.name() for e in self.projection)}]"


class UDFProject(LogicalPlan):
    """A project isolated because it contains an expensive Python UDF
    (reference: ops/udf_project.rs, created by the SplitUDFs optimizer rule).

    Holds exactly one UDF expression plus passthrough columns.
    """

    def __init__(self, input: LogicalPlan, udf_expr: Expression, passthrough: List[Expression]):
        super().__init__()
        self.input = input
        self.udf_expr = udf_expr
        self.passthrough = list(passthrough)

    def children(self):
        return [self.input]

    def with_children(self, children):
        return UDFProject(children[0], self.udf_expr, self.passthrough)

    def _compute_schema(self) -> Schema:
        in_schema = self.input.schema
        fields = [e.to_field(in_schema) for e in self.passthrough]
        fields.append(self.udf_expr.to_field(in_schema))
        return Schema(fields)

    def describe(self) -> str:
        return f"UDFProject[{self.udf_expr.name()}]"


class Filter(LogicalPlan):
    def __init__(self, input: LogicalPlan, predicate: Expression,
                 keep: Optional[List[str]] = None):
        """keep: optional output-column subset (set by the column-pruning pass
        when downstream needs fewer columns than the predicate reads) — the
        executor then materializes only these columns after the mask."""
        super().__init__()
        self.input = input
        self.predicate = predicate
        self.keep = keep

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Filter(children[0], self.predicate, self.keep)

    def _compute_schema(self) -> Schema:
        dt = self.predicate.get_type(self.input.schema)
        if not dt.is_boolean() and not dt.is_null():
            raise ValueError(f"filter predicate must be boolean, got {dt}")
        if self.keep is not None:
            return Schema([self.input.schema[c] for c in self.keep])
        return self.input.schema

    def describe(self) -> str:
        return f"Filter[{self.predicate}]"


class Explode(LogicalPlan):
    def __init__(self, input: LogicalPlan, to_explode: List[Expression]):
        super().__init__()
        self.input = input
        self.to_explode = list(to_explode)

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Explode(children[0], self.to_explode)

    def _compute_schema(self) -> Schema:
        in_schema = self.input.schema
        exploded = {}
        for e in self.to_explode:
            f = e.to_field(in_schema)
            inner = f.dtype.inner if f.dtype.is_list() else f.dtype
            exploded[f.name] = Field(f.name, inner)
        fields = [exploded.get(f.name, f) for f in in_schema.fields]
        return Schema(fields)

    def describe(self) -> str:
        return f"Explode[{', '.join(e.name() for e in self.to_explode)}]"


class Unpivot(LogicalPlan):
    def __init__(self, input: LogicalPlan, ids: List[Expression], values: List[Expression],
                 variable_name: str, value_name: str):
        super().__init__()
        self.input = input
        self.ids = list(ids)
        self.values = list(values)
        self.variable_name = variable_name
        self.value_name = value_name

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Unpivot(children[0], self.ids, self.values, self.variable_name, self.value_name)

    def _compute_schema(self) -> Schema:
        in_schema = self.input.schema
        fields = [e.to_field(in_schema) for e in self.ids]
        value_fields = [e.to_field(in_schema) for e in self.values]
        if not value_fields:
            raise ValueError("unpivot requires at least one value column")
        vt = value_fields[0].dtype
        for f in value_fields[1:]:
            if f.dtype != vt:
                vt = DataType.common_supertype(vt, f.dtype)
        fields.append(Field(self.variable_name, DataType.string()))
        fields.append(Field(self.value_name, vt))
        return Schema(fields)


class Sample(LogicalPlan):
    def __init__(self, input: LogicalPlan, fraction: float, with_replacement: bool, seed: Optional[int]):
        super().__init__()
        self.input = input
        self.fraction = fraction
        self.with_replacement = with_replacement
        self.seed = seed

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Sample(children[0], self.fraction, self.with_replacement, self.seed)

    def _compute_schema(self) -> Schema:
        return self.input.schema


class MonotonicallyIncreasingId(LogicalPlan):
    def __init__(self, input: LogicalPlan, column_name: str = "id"):
        super().__init__()
        self.input = input
        self.column_name = column_name

    def children(self):
        return [self.input]

    def with_children(self, children):
        return MonotonicallyIncreasingId(children[0], self.column_name)

    def _compute_schema(self) -> Schema:
        return Schema([Field(self.column_name, DataType.uint64())] + list(self.input.schema.fields))


# ======================================================================================
# Cardinality ops
# ======================================================================================


class Limit(LogicalPlan):
    def __init__(self, input: LogicalPlan, limit: int):
        super().__init__()
        self.input = input
        self.limit = limit

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Limit(children[0], self.limit)

    def _compute_schema(self) -> Schema:
        return self.input.schema

    def describe(self) -> str:
        return f"Limit[{self.limit}]"


class Offset(LogicalPlan):
    def __init__(self, input: LogicalPlan, offset: int):
        super().__init__()
        self.input = input
        self.offset = offset

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Offset(children[0], self.offset)

    def _compute_schema(self) -> Schema:
        return self.input.schema


class Distinct(LogicalPlan):
    def __init__(self, input: LogicalPlan, on: Optional[List[Expression]] = None):
        super().__init__()
        self.input = input
        self.on = on  # None = all columns

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Distinct(children[0], self.on)

    def _compute_schema(self) -> Schema:
        return self.input.schema


# ======================================================================================
# Ordering
# ======================================================================================


class Sort(LogicalPlan):
    def __init__(self, input: LogicalPlan, sort_by: List[Expression], descending: List[bool],
                 nulls_first: Optional[List[bool]] = None):
        super().__init__()
        self.input = input
        self.sort_by = list(sort_by)
        self.descending = list(descending)
        self.nulls_first = list(nulls_first) if nulls_first is not None else [d for d in self.descending]

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Sort(children[0], self.sort_by, self.descending, self.nulls_first)

    def _compute_schema(self) -> Schema:
        return self.input.schema

    def describe(self) -> str:
        keys = ", ".join(
            f"{e.name()} {'desc' if d else 'asc'}" for e, d in zip(self.sort_by, self.descending)
        )
        return f"Sort[{keys}]"


class TopN(LogicalPlan):
    """Sort + Limit(+Offset) fused (reference: ops/top_n.rs, detected by optimizer)."""

    def __init__(self, input: LogicalPlan, sort_by: List[Expression], descending: List[bool],
                 nulls_first: List[bool], limit: int, offset: int = 0):
        super().__init__()
        self.input = input
        self.sort_by = list(sort_by)
        self.descending = list(descending)
        self.nulls_first = list(nulls_first)
        self.limit = limit
        self.offset = offset

    def children(self):
        return [self.input]

    def with_children(self, children):
        return TopN(children[0], self.sort_by, self.descending, self.nulls_first, self.limit, self.offset)

    def _compute_schema(self) -> Schema:
        return self.input.schema

    def describe(self) -> str:
        return f"TopN[{self.limit}]"


# ======================================================================================
# Aggregation
# ======================================================================================


class MapGroups(LogicalPlan):
    """Apply a UDF expression to each group's rows; output = group keys
    (replicated per emitted row) + the UDF's column (reference:
    GroupedDataFrame.map_groups, daft/dataframe/dataframe.py)."""

    def __init__(self, input: LogicalPlan, groupby: List[Expression],
                 udf_expr: Expression):
        super().__init__()
        self.input = input
        self.groupby = list(groupby)
        self.udf_expr = udf_expr

    def children(self):
        return [self.input]

    def with_children(self, children):
        return MapGroups(children[0], self.groupby, self.udf_expr)

    def _compute_schema(self) -> Schema:
        in_schema = self.input.schema
        fields = [e.to_field(in_schema) for e in self.groupby]
        fields.append(self.udf_expr.to_field(in_schema))
        return Schema(fields)

    def describe(self) -> str:
        g = ", ".join(e.name() for e in self.groupby)
        return f"MapGroups[groupby=({g}) udf={self.udf_expr.name()}]"


class Aggregate(LogicalPlan):
    def __init__(self, input: LogicalPlan, groupby: List[Expression], aggregations: List[Expression]):
        super().__init__()
        self.input = input
        self.groupby = list(groupby)
        self.aggregations = list(aggregations)

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Aggregate(children[0], self.groupby, self.aggregations)

    def _compute_schema(self) -> Schema:
        in_schema = self.input.schema
        fields = [e.to_field(in_schema) for e in self.groupby]
        fields += [e.to_field(in_schema) for e in self.aggregations]
        return Schema(fields)

    def describe(self) -> str:
        g = ", ".join(e.name() for e in self.groupby)
        a = ", ".join(e.name() for e in self.aggregations)
        return f"Aggregate[groupby=({g}) aggs=({a})]"


class Pivot(LogicalPlan):
    def __init__(self, input: LogicalPlan, groupby: List[Expression], pivot_col: Expression,
                 value_col: Expression, agg_op: str, names: List[str]):
        super().__init__()
        self.input = input
        self.groupby = list(groupby)
        self.pivot_col = pivot_col
        self.value_col = value_col
        self.agg_op = agg_op
        self.names = list(names)

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Pivot(children[0], self.groupby, self.pivot_col, self.value_col, self.agg_op, self.names)

    def _compute_schema(self) -> Schema:
        in_schema = self.input.schema
        fields = [e.to_field(in_schema) for e in self.groupby]
        agg = AggExpr(self.agg_op, self.value_col)
        value_field = agg.to_field(in_schema)
        for n in self.names:
            fields.append(Field(n, value_field.dtype))
        return Schema(fields)


class Window(LogicalPlan):
    """Window functions over a WindowSpec (reference: ops/window.rs + expr/window.rs:92)."""

    def __init__(self, input: LogicalPlan, window_exprs: List[Expression], spec: Any):
        super().__init__()
        self.input = input
        self.window_exprs = list(window_exprs)  # WindowExpr nodes with output names
        self.spec = spec

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Window(children[0], self.window_exprs, self.spec)

    def _compute_schema(self) -> Schema:
        in_schema = self.input.schema
        fields = list(in_schema.fields)
        for e in self.window_exprs:
            fields.append(e.to_field(in_schema))
        return Schema(fields)


# ======================================================================================
# Multi-input ops
# ======================================================================================


class Concat(LogicalPlan):
    def __init__(self, inputs: List[LogicalPlan]):
        super().__init__()
        if not inputs:
            raise ValueError("concat of zero plans")
        self.inputs = list(inputs)
        s0 = inputs[0].schema
        for p in inputs[1:]:
            if p.schema.column_names() != s0.column_names():
                raise ValueError(
                    f"concat requires matching schemas: {s0.column_names()} vs {p.schema.column_names()}"
                )

    def children(self):
        return self.inputs

    def with_children(self, children):
        return Concat(children)

    def _compute_schema(self) -> Schema:
        return self.inputs[0].schema


class Join(LogicalPlan):
    JOIN_TYPES = ("inner", "left", "right", "outer", "anti", "semi", "cross")

    def __init__(self, left: LogicalPlan, right: LogicalPlan, left_on: List[Expression],
                 right_on: List[Expression], how: str = "inner",
                 prefix: Optional[str] = None, suffix: Optional[str] = None,
                 strategy: Optional[str] = None, null_equals_null: bool = False):
        super().__init__()
        if how not in self.JOIN_TYPES:
            raise ValueError(f"unknown join type {how!r}")
        self.left = left
        self.right = right
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.how = how
        self.prefix = prefix
        self.suffix = suffix
        self.strategy = strategy  # None=auto, 'hash', 'sort_merge', 'broadcast', 'cross'
        # SQL set ops (EXCEPT/INTERSECT) match NULL keys to NULL keys
        self.null_equals_null = null_equals_null

    def children(self):
        return [self.left, self.right]

    def with_children(self, children):
        return Join(children[0], children[1], self.left_on, self.right_on, self.how,
                    self.prefix, self.suffix, self.strategy, self.null_equals_null)

    def output_naming(self):
        """(merged_keys, right_rename): join keys with identical names merge into one
        output column; clashing right value columns get prefix/suffix or 'right.'."""
        left_names = set(self.left.schema.column_names())
        merged_keys = set()
        for lo, ro in zip(self.left_on, self.right_on):
            if lo.name() == ro.name():
                merged_keys.add(ro.name())
        right_rename = {}
        for f in self.right.schema.fields:
            if f.name in merged_keys:
                continue
            if f.name in left_names:
                if self.prefix is not None or self.suffix is not None:
                    right_rename[f.name] = f"{self.prefix or ''}{f.name}{self.suffix or ''}"
                else:
                    right_rename[f.name] = f"right.{f.name}"
        return merged_keys, right_rename

    def _renamed_right_fields(self) -> List[Field]:
        merged_keys, right_rename = self.output_naming()
        return [
            Field(right_rename.get(f.name, f.name), f.dtype)
            for f in self.right.schema.fields
            if f.name not in merged_keys
        ]

    def _compute_schema(self) -> Schema:
        if self.how in ("anti", "semi"):
            return self.left.schema
        fields = list(self.left.schema.fields)
        fields += self._renamed_right_fields()
        return Schema(fields)

    def describe(self) -> str:
        keys = ", ".join(
            f"{l.name()}={r.name()}" for l, r in zip(self.left_on, self.right_on)
        )
        return f"Join[{self.how} on ({keys}) strategy={self.strategy or 'auto'}]"


# ======================================================================================
# Partitioning ops
# ======================================================================================


class Repartition(LogicalPlan):
    """Hash/random/range repartition (reference: ops/repartition.rs + RepartitionSpec)."""

    def __init__(self, input: LogicalPlan, num_partitions: Optional[int], scheme: str,
                 by: Optional[List[Expression]] = None):
        super().__init__()
        if scheme not in ("hash", "random", "range", "into"):
            raise ValueError(f"unknown repartition scheme {scheme!r}")
        self.input = input
        self.num_partitions = num_partitions
        self.scheme = scheme
        self.by = list(by) if by else []

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Repartition(children[0], self.num_partitions, self.scheme, self.by)

    def _compute_schema(self) -> Schema:
        return self.input.schema

    def describe(self) -> str:
        return f"Repartition[{self.scheme} n={self.num_partitions}]"


class IntoPartitions(LogicalPlan):
    def __init__(self, input: LogicalPlan, num_partitions: int):
        super().__init__()
        self.input = input
        self.num_partitions = num_partitions

    def children(self):
        return [self.input]

    def with_children(self, children):
        return IntoPartitions(children[0], self.num_partitions)

    def _compute_schema(self) -> Schema:
        return self.input.schema


class IntoBatches(LogicalPlan):
    def __init__(self, input: LogicalPlan, batch_size: int):
        super().__init__()
        self.input = input
        self.batch_size = batch_size

    def children(self):
        return [self.input]

    def with_children(self, children):
        return IntoBatches(children[0], self.batch_size)

    def _compute_schema(self) -> Schema:
        return self.input.schema


# ======================================================================================
# Sinks
# ======================================================================================


class Sink(LogicalPlan):
    """Write sink (reference: ops/sink.rs; SinkInfo Output/Catalog/DataSink).

    `info` is a WriteInfo from daft_tpu.io.writers describing format/path/options.
    The output schema is the write-result manifest (file paths + row counts).
    """

    def __init__(self, input: LogicalPlan, info: Any):
        super().__init__()
        self.input = input
        self.info = info

    def children(self):
        return [self.input]

    def with_children(self, children):
        return Sink(children[0], self.info)

    def _compute_schema(self) -> Schema:
        return self.info.result_schema()

    def describe(self) -> str:
        return f"Sink[{self.info}]"
