"""Plan-level cardinality/size estimation for cost-based decisions.

Reference parity: src/daft-logical-plan/src/stats.rs (ApproxStats propagated
by enrich_with_stats) + src/daft-stats. Estimates drive greedy join
reordering, broadcast-join selection, and distributed-planner choices. All
numbers are approximations — correctness never depends on them.
"""

from __future__ import annotations

from typing import Optional

from ..expressions import ColumnRef, Expression
from ..expressions.expressions import Alias, Between, BinaryOp, IsIn, Literal, UnaryOp
from . import logical as lp

# default selectivities (reference stats.rs uses similar fixed factors)
_SEL_EQ = 0.1
_SEL_RANGE = 0.3
_SEL_ISIN = 0.2
_SEL_DEFAULT = 0.25


def _dtype_width(dt) -> int:
    if dt.is_boolean():
        return 1
    if dt.is_string() or dt.is_binary():
        return 24
    if dt.is_list() or dt.is_struct() or dt.is_map():
        return 64
    return 8


def row_width(schema) -> int:
    return max(sum(_dtype_width(f.dtype) for f in schema), 1)


def selectivity(pred: Expression) -> float:
    """Estimated fraction of rows a predicate keeps."""
    if isinstance(pred, Alias):
        return selectivity(pred.child)
    if isinstance(pred, BinaryOp):
        if pred.op == "and":
            return selectivity(pred.left) * selectivity(pred.right)
        if pred.op == "or":
            return min(1.0, selectivity(pred.left) + selectivity(pred.right))
        if pred.op == "eq":
            return _SEL_EQ
        if pred.op in ("lt", "le", "gt", "ge"):
            return _SEL_RANGE
        if pred.op == "neq":
            return 1.0 - _SEL_EQ
    if isinstance(pred, Between):
        return _SEL_RANGE
    if isinstance(pred, IsIn):
        return min(1.0, _SEL_EQ * max(len(pred.items), 1))
    if isinstance(pred, UnaryOp) and pred.op in ("is_null", "not_null"):
        return 0.5
    return _SEL_DEFAULT


def estimate_rows(plan: lp.LogicalPlan) -> Optional[float]:
    """Approximate output cardinality of a logical plan (None = unknown)."""
    if isinstance(plan, lp.InMemorySource):
        return float(sum(p.num_rows for p in plan.partitions))
    if isinstance(plan, lp.ScanSource):
        try:
            return plan.scan_op.approx_num_rows(plan.pushdowns)
        except Exception:  # lint: ignore[broad-except] -- row estimate is advisory
            return None
    if isinstance(plan, lp.Filter):
        child = estimate_rows(plan.input)
        return None if child is None else child * selectivity(plan.predicate)
    if isinstance(plan, lp.Join):
        l = estimate_rows(plan.left)
        r = estimate_rows(plan.right)
        if l is None or r is None:
            return None
        if plan.how == "cross":
            return l * r
        if plan.how in ("semi", "anti"):
            return l * 0.5
        if plan.how == "inner":
            # FK-join assumption: result ~ the larger side
            return max(l, r)
        if plan.how == "left":
            return l  # lower bound; duplicate right keys can fan out
        if plan.how == "right":
            return r
        return l + r  # outer
    if isinstance(plan, lp.Aggregate):
        child = estimate_rows(plan.input)
        if child is None:
            return None
        if not plan.groupby:
            return 1.0
        return max(child ** 0.7, 1.0)  # sublinear distinct-group heuristic
    if isinstance(plan, lp.Distinct):
        child = estimate_rows(plan.input)
        return None if child is None else max(child * 0.3, 1.0)
    if isinstance(plan, lp.Limit):
        child = estimate_rows(plan.input)
        lim = float(plan.limit) if plan.limit >= 0 else None
        if child is None:
            return lim
        return min(child, lim) if lim is not None else child
    if isinstance(plan, lp.Sample):
        child = estimate_rows(plan.input)
        return None if child is None else child * plan.fraction
    if isinstance(plan, lp.Concat):
        vals = [estimate_rows(c) for c in plan.inputs]
        if any(v is None for v in vals):
            return None
        return float(sum(vals))
    if isinstance(plan, lp.Explode):
        child = estimate_rows(plan.input)
        return None if child is None else child * 4.0
    children = plan.children()
    if len(children) == 1:
        return estimate_rows(children[0])
    return None


def estimate_bytes(plan: lp.LogicalPlan) -> Optional[float]:
    rows = estimate_rows(plan)
    if rows is None:
        return None
    return rows * row_width(plan.schema)


_DISTINCT_SAMPLE = 8192


def estimate_distinct(plan: lp.LogicalPlan, column: str) -> Optional[float]:
    """Approximate distinct-value count of one column (Selinger V(R, a)).

    In-memory sources sample the first rows (near-saturated samples
    extrapolate); filters cap V at the estimated surviving row count; unknown
    sources return None (callers fall back to the unique-key assumption).
    """
    rows = estimate_rows(plan)
    src = plan
    while True:
        if isinstance(src, lp.InMemorySource):
            for p in src.partitions:
                for b in p.batches:
                    if column in b.column_names() and b.num_rows > 0:
                        series = b.get_column(column)
                        # cache on the Series (immutable): repeated optimizes
                        # of queries over resident tables sample exactly once
                        cache = getattr(series, "_device_cache", None)
                        if cache is None:
                            cache = {}
                            object.__setattr__(series, "_device_cache", cache)
                        k = cache.get(("distinct_est",))
                        if k is None:
                            k = _chao1_distinct(series, b.num_rows)
                            cache[("distinct_est",)] = k
                        return min(k, rows) if rows is not None else k
            return None
        children = src.children()
        if len(children) == 1 and column in children[0].schema.column_names():
            src = children[0]
            continue
        return None


def _chao1_distinct(series, n_rows: int) -> float:
    """Chao1 richness estimate from a STRIDED sample (head samples are biased
    on clustered keys like sequential order ids): D ~= k + f1^2 / (2*f2);
    an all-singleton sample means the column looks key-like -> D ~= n_rows.
    Naive linear extrapolation (the previous scheme) overestimated columns
    whose true cardinality is near the sample size by orders of magnitude."""
    import numpy as np

    if n_rows <= _DISTINCT_SAMPLE:
        sample = series
    else:
        step = n_rows // _DISTINCT_SAMPLE
        idx = np.arange(0, n_rows, step, dtype=np.int64)[:_DISTINCT_SAMPLE]
        sample = series.take(idx)
    try:
        vals = sample.to_numpy()
        _, counts = np.unique(vals, return_counts=True)
    except Exception:  # lint: ignore[broad-except] -- falls through to the python-object path
        from collections import Counter

        counts = np.array(list(Counter(sample.to_pylist()).values()))
    k = float(len(counts))
    if n_rows <= _DISTINCT_SAMPLE:
        return k
    f1 = float((counts == 1).sum())
    f2 = float((counts == 2).sum())
    if f2 > 0:
        est = k + f1 * f1 / (2.0 * f2)
    elif f1 >= k * 0.95:
        est = float(n_rows)  # (nearly) all singletons: treat as a key column
    else:
        est = k
    return min(est, float(n_rows))


def estimate_join_result(left_rows: float, right_rows: float,
                         v_left: Optional[float], v_right: Optional[float]) -> float:
    """Selinger equi-join estimate: |L||R| / max(V(L,a), V(R,b)); unknown V
    falls back to the unique-key (FK) assumption on that side."""
    vl = v_left if v_left is not None else left_rows
    vr = v_right if v_right is not None else right_rows
    denom = max(vl, vr, 1.0)
    return max(left_rows * right_rows / denom, 1.0)
