"""LogicalPlanBuilder: the fluent façade every DataFrame method appends through.

Reference parity: daft/logical/builder.py:54 + src/daft-logical-plan/src/builder/mod.rs:61.
Expression normalization (strings → col(), literals → lit()) happens here so the
plan IR only ever holds Expression nodes.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

from ..expressions import AggExpr, Alias, ColumnRef, Expression, col, lit
from ..schema import Schema
from . import logical as lp

ColumnInput = Union[str, Expression]


def _to_expr(c: ColumnInput) -> Expression:
    if isinstance(c, Expression):
        return c
    if isinstance(c, str):
        return col(c)
    return lit(c)


def _to_exprs(cols: Sequence[ColumnInput]) -> List[Expression]:
    out: List[Expression] = []
    for c in cols:
        if isinstance(c, (list, tuple)):
            out.extend(_to_exprs(c))
        else:
            out.append(_to_expr(c))
    return out


class LogicalPlanBuilder:
    def __init__(self, plan: lp.LogicalPlan):
        self._plan = plan

    # ---- constructors ------------------------------------------------------------
    @classmethod
    def from_in_memory(cls, schema: Schema, partitions: List[Any]) -> "LogicalPlanBuilder":
        return cls(lp.InMemorySource(schema, partitions))

    @classmethod
    def from_scan(cls, scan_op: Any) -> "LogicalPlanBuilder":
        return cls(lp.ScanSource(scan_op))

    # ---- accessors ---------------------------------------------------------------
    @property
    def plan(self) -> lp.LogicalPlan:
        return self._plan

    def schema(self) -> Schema:
        return self._plan.schema

    def _next(self, plan: lp.LogicalPlan) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(plan)

    # ---- row ops -----------------------------------------------------------------
    def select(self, to_select: Sequence[ColumnInput]) -> "LogicalPlanBuilder":
        return self._next(lp.Project(self._plan, _to_exprs(to_select)))

    def with_columns(self, new_columns: Sequence[Expression]) -> "LogicalPlanBuilder":
        existing = self._plan.schema.column_names()
        new_names = {e.name() for e in new_columns}
        projection: List[Expression] = [col(n) for n in existing if n not in new_names]
        projection.extend(new_columns)
        return self.select(projection)

    def exclude(self, names: Sequence[str]) -> "LogicalPlanBuilder":
        keep = [c for c in self._plan.schema.column_names() if c not in set(names)]
        return self.select([col(n) for n in keep])

    def rename(self, mapping: dict) -> "LogicalPlanBuilder":
        projection = []
        for n in self._plan.schema.column_names():
            projection.append(Alias(col(n), mapping[n]) if n in mapping else col(n))
        return self.select(projection)

    def filter(self, predicate: Expression) -> "LogicalPlanBuilder":
        return self._next(lp.Filter(self._plan, _to_expr(predicate)))

    def explode(self, to_explode: Sequence[ColumnInput]) -> "LogicalPlanBuilder":
        return self._next(lp.Explode(self._plan, _to_exprs(to_explode)))

    def unpivot(self, ids: Sequence[ColumnInput], values: Sequence[ColumnInput],
                variable_name: str, value_name: str) -> "LogicalPlanBuilder":
        return self._next(
            lp.Unpivot(self._plan, _to_exprs(ids), _to_exprs(values), variable_name, value_name)
        )

    def sample(self, fraction: float, with_replacement: bool = False,
               seed: Optional[int] = None) -> "LogicalPlanBuilder":
        return self._next(lp.Sample(self._plan, fraction, with_replacement, seed))

    def add_monotonically_increasing_id(self, column_name: str = "id") -> "LogicalPlanBuilder":
        return self._next(lp.MonotonicallyIncreasingId(self._plan, column_name))

    # ---- cardinality -------------------------------------------------------------
    def limit(self, n: int) -> "LogicalPlanBuilder":
        return self._next(lp.Limit(self._plan, n))

    def offset(self, n: int) -> "LogicalPlanBuilder":
        return self._next(lp.Offset(self._plan, n))

    def distinct(self, on: Optional[Sequence[ColumnInput]] = None) -> "LogicalPlanBuilder":
        return self._next(lp.Distinct(self._plan, _to_exprs(on) if on else None))

    # ---- ordering ----------------------------------------------------------------
    def sort(self, sort_by: Sequence[ColumnInput], descending: Union[bool, List[bool]] = False,
             nulls_first: Optional[Union[bool, List[bool]]] = None) -> "LogicalPlanBuilder":
        exprs = _to_exprs(sort_by)
        desc = [descending] * len(exprs) if isinstance(descending, bool) else list(descending)
        nf: Optional[List[bool]]
        if nulls_first is None:
            nf = None
        elif isinstance(nulls_first, bool):
            nf = [nulls_first] * len(exprs)
        else:
            nf = list(nulls_first)
        return self._next(lp.Sort(self._plan, exprs, desc, nf))

    # ---- aggregation -------------------------------------------------------------
    def aggregate(self, aggs: Sequence[Expression], groupby: Sequence[ColumnInput]) -> "LogicalPlanBuilder":
        return self._next(lp.Aggregate(self._plan, _to_exprs(groupby), list(aggs)))

    def pivot(self, groupby: Sequence[ColumnInput], pivot_col: ColumnInput, value_col: ColumnInput,
              agg_op: str, names: List[str]) -> "LogicalPlanBuilder":
        return self._next(
            lp.Pivot(self._plan, _to_exprs(groupby), _to_expr(pivot_col), _to_expr(value_col),
                     agg_op, names)
        )

    def window(self, window_exprs: Sequence[Expression], spec: Any) -> "LogicalPlanBuilder":
        return self._next(lp.Window(self._plan, list(window_exprs), spec))

    # ---- multi-input -------------------------------------------------------------
    def concat(self, other: "LogicalPlanBuilder") -> "LogicalPlanBuilder":
        return self._next(lp.Concat([self._plan, other._plan]))

    def join(self, right: "LogicalPlanBuilder", left_on: Sequence[ColumnInput],
             right_on: Sequence[ColumnInput], how: str = "inner",
             prefix: Optional[str] = None, suffix: Optional[str] = None,
             strategy: Optional[str] = None,
             null_equals_null: bool = False) -> "LogicalPlanBuilder":
        return self._next(
            lp.Join(self._plan, right._plan, _to_exprs(left_on), _to_exprs(right_on),
                    how, prefix, suffix, strategy, null_equals_null)
        )

    def cross_join(self, right: "LogicalPlanBuilder", prefix: Optional[str] = None,
                   suffix: Optional[str] = None) -> "LogicalPlanBuilder":
        return self._next(lp.Join(self._plan, right._plan, [], [], "cross", prefix, suffix))

    # ---- partitioning ------------------------------------------------------------
    def repartition(self, num_partitions: Optional[int], scheme: str = "hash",
                    by: Optional[Sequence[ColumnInput]] = None) -> "LogicalPlanBuilder":
        return self._next(
            lp.Repartition(self._plan, num_partitions, scheme, _to_exprs(by) if by else None)
        )

    def into_partitions(self, num_partitions: int) -> "LogicalPlanBuilder":
        return self._next(lp.IntoPartitions(self._plan, num_partitions))

    def into_batches(self, batch_size: int) -> "LogicalPlanBuilder":
        return self._next(lp.IntoBatches(self._plan, batch_size))

    # ---- sinks -------------------------------------------------------------------
    def write(self, info: Any) -> "LogicalPlanBuilder":
        return self._next(lp.Sink(self._plan, info))

    # ---- optimize ----------------------------------------------------------------
    def optimize(self, config: Any = None) -> "LogicalPlanBuilder":
        # prepared-query fast path (daft_tpu/serving/prepared.py): a builder
        # already holding an optimized plan short-circuits, so a runner
        # handed a prepared plan never re-runs the optimizer rules
        if getattr(self, "_preoptimized", False):
            return self
        from .optimizer import Optimizer

        return self._next(Optimizer(config).optimize(self._plan))
