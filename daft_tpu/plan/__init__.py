from .logical import (
    Aggregate,
    Concat,
    Distinct,
    Explode,
    Filter,
    InMemorySource,
    IntoBatches,
    IntoPartitions,
    Join,
    Limit,
    LogicalPlan,
    MonotonicallyIncreasingId,
    Offset,
    Pivot,
    Project,
    Repartition,
    Sample,
    ScanSource,
    Sink,
    Sort,
    TopN,
    UDFProject,
    Unpivot,
    Window,
)
from .builder import LogicalPlanBuilder

__all__ = [
    "LogicalPlan", "InMemorySource", "ScanSource", "Project", "UDFProject", "Filter",
    "Limit", "Offset", "Explode", "Unpivot", "Sort", "Repartition", "IntoPartitions",
    "Distinct", "Aggregate", "Pivot", "Concat", "Join", "Sink", "Sample",
    "MonotonicallyIncreasingId", "Window", "TopN", "IntoBatches", "LogicalPlanBuilder",
]
