"""Rule-based logical optimizer.

Reference parity: src/daft-logical-plan/src/optimization/optimizer.rs:60,309
(RuleBatch fixed-point pass manager) and optimization/rules/*. Rules are
functions plan→plan|None applied bottom-up to fixed point per batch.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..expressions import ColumnRef, Expression, col
from . import logical as lp

Rule = Callable[[lp.LogicalPlan], Optional[lp.LogicalPlan]]


class RuleBatch:
    def __init__(self, name: str, rules: List[Rule], max_passes: int = 5):
        self.name = name
        self.rules = rules
        self.max_passes = max_passes


class Optimizer:
    def __init__(self, config=None):
        self.config = config
        self.batches = default_rule_batches(config)

    def optimize(self, plan: lp.LogicalPlan) -> lp.LogicalPlan:
        for batch in self.batches:
            for _ in range(batch.max_passes):
                changed = False
                for rule in batch.rules:
                    new = plan.transform_up(_track(rule))
                    if new is not plan:
                        plan = new
                        changed = True
                if not changed:
                    break
        return plan


def _track(rule: Rule) -> Rule:
    def wrapped(node):
        out = rule(node)
        return out

    return wrapped


# ======================================================================================
# Rules
# ======================================================================================


def rule_drop_trivial_filter(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Filter(lit(True)) → input (part of SimplifyExpressions in the reference)."""
    if isinstance(node, lp.Filter) and node.predicate.is_literal_true():
        return node.input
    return None


def rule_merge_filters(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Filter(Filter(x, a), b) → Filter(x, a & b)."""
    if isinstance(node, lp.Filter) and isinstance(node.input, lp.Filter):
        return lp.Filter(node.input.input, node.input.predicate & node.predicate)
    return None


def rule_merge_limits(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    if isinstance(node, lp.Limit) and isinstance(node.input, lp.Limit):
        return lp.Limit(node.input.input, min(node.limit, node.input.limit))
    return None


def rule_push_filter_into_scan(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Filter over a ScanSource whose operator can absorb filters → pushdown.

    Reference: rules/push_down_filter.rs. We keep the Filter node (scans may apply
    pushdown filters only approximately, e.g. via zone maps) unless the scan
    promises exact application; translate() checks task.filters_applied.
    """
    if not (isinstance(node, lp.Filter) and isinstance(node.input, lp.ScanSource)):
        return None
    scan = node.input
    if not scan.scan_op.can_absorb_filter():
        return None
    pd = scan.pushdowns
    # idempotence: the Filter node is kept above the scan (pushdown filters may be
    # applied only approximately), so skip once this predicate is already pushed
    if pd.filters is not None and repr(pd.filters) == repr(node.predicate):
        return None
    from ..io.scan import Pushdowns

    if pd.filters is not None:
        new_filters = pd.filters & node.predicate
    else:
        new_filters = node.predicate
    new_scan = lp.ScanSource(scan.scan_op, Pushdowns(pd.columns, new_filters, pd.limit))
    return lp.Filter(new_scan, new_filters)


def rule_push_limit_into_scan(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    if not (isinstance(node, lp.Limit) and isinstance(node.input, lp.ScanSource)):
        return None
    scan = node.input
    pd = scan.pushdowns
    if pd.filters is not None:
        return None  # limit-after-filter can't be pushed below the filter
    if pd.limit is not None and pd.limit <= node.limit:
        return None
    from ..io.scan import Pushdowns

    new_scan = lp.ScanSource(scan.scan_op, Pushdowns(pd.columns, pd.filters, node.limit))
    return lp.Limit(new_scan, node.limit)


def rule_push_limit_through(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Limit commutes with Project (row-preserving)."""
    if isinstance(node, lp.Limit) and isinstance(node.input, lp.Project):
        proj = node.input
        if not any(e.has_udf() for e in proj.projection):
            return lp.Project(lp.Limit(proj.input, node.limit), proj.projection)
    return None


def rule_detect_topn(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Limit(Sort) → TopN (reference: extract TopN)."""
    if isinstance(node, lp.Limit) and isinstance(node.input, lp.Sort):
        s = node.input
        return lp.TopN(s.input, s.sort_by, s.descending, s.nulls_first, node.limit)
    if (isinstance(node, lp.Limit) and isinstance(node.input, lp.Offset)
            and isinstance(node.input.input, lp.Sort)):
        s = node.input.input
        return lp.TopN(s.input, s.sort_by, s.descending, s.nulls_first,
                       node.limit, node.input.offset)
    return None


def _projection_is_passthrough(projection: List[Expression], input_schema) -> bool:
    names = input_schema.column_names()
    if len(projection) != len(names):
        return False
    for e, n in zip(projection, names):
        if not (isinstance(e, ColumnRef) and e._name == n):
            return False
    return True


def rule_drop_noop_project(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    if isinstance(node, lp.Project) and _projection_is_passthrough(node.projection, node.input.schema):
        return node.input
    return None


def rule_column_pruning(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Push column selection into ScanSource when a Project only needs a subset.

    Reference: rules/push_down_projection.rs (materialized as scan pushdown here;
    general projection pushdown through intermediate ops lands with M2).
    """
    if not (isinstance(node, lp.Project) and isinstance(node.input, lp.ScanSource)):
        return None
    scan = node.input
    needed: List[str] = []
    for e in node.projection:
        for c in e.referenced_columns():
            if c not in needed:
                needed.append(c)
    if scan.pushdowns.filters is not None:
        for c in scan.pushdowns.filters.referenced_columns():
            if c not in needed:
                needed.append(c)
    all_cols = scan.scan_op.schema().column_names()
    needed = [c for c in all_cols if c in set(needed)]
    if len(needed) >= len(scan.schema.column_names()):
        return None
    from ..io.scan import Pushdowns

    pd = scan.pushdowns
    new_scan = lp.ScanSource(scan.scan_op, Pushdowns(needed, pd.filters, pd.limit))
    return lp.Project(new_scan, node.projection)


def rule_split_udfs(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Isolate UDF-bearing expressions into their own UDFProject nodes
    (reference: rules/split_udfs.rs) so host UDFs don't break device stage fusion.

    Extracts EVERY UDF expression in one application (stacked UDFProject nodes),
    so isolation doesn't depend on the batch's pass budget; each UDF output gets
    a unique internal name so sibling expressions referencing a same-named input
    column are unaffected.
    """
    if not isinstance(node, lp.Project):
        return None
    udf_exprs = [e for e in node.projection if e.has_udf()]
    if not udf_exprs or len(node.projection) == len(udf_exprs) == 1:
        return None
    current = node.input
    projection = list(node.projection)
    for target in udf_exprs:
        out_name = target.name()
        input_cols = current.schema.column_names()
        taken = set(input_cols) | {e.name() for e in projection}
        internal = f"__udf__{out_name}"
        while internal in taken:
            internal = "_" + internal
        passthrough = [col(c) for c in input_cols]
        current = lp.UDFProject(current, target.alias(internal), passthrough)
        projection = [
            col(internal).alias(out_name) if e is target else e for e in projection
        ]
    return lp.Project(current, projection)


def rule_extract_windows(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Pull WindowExpr nodes out of projections into Window plan nodes
    (reference: rules/extract_window_function.rs)."""
    if not isinstance(node, lp.Project):
        return None
    from ..expressions.expressions import WindowExpr

    found: List = []
    seen_ids = set()
    for e in node.projection:
        for sub in e.walk():
            if isinstance(sub, WindowExpr) and id(sub) not in seen_ids:
                seen_ids.add(id(sub))
                found.append(sub)
    if not found:
        return None

    # group by spec *content* so equal-but-distinct Window() objects share one
    # sort+segment pass, and dedupe identical window computations within a spec
    by_spec = {}
    replacement = {}
    for w in found:
        spec_key = repr(w.spec)
        spec, ws = by_spec.setdefault(spec_key, (w.spec, {}))
        expr_key = (w.func, repr(w.child), repr(sorted(w.params.items(), key=str)))
        if expr_key not in ws:
            ws[expr_key] = (f"__window_{len(replacement)}", w)
        replacement[id(w)] = ws[expr_key][0]

    input_node = node.input
    for spec, ws in by_spec.values():
        named = [w.alias(internal) for internal, w in ws.values()]
        input_node = lp.Window(input_node, named, spec)

    def rewrite(e: Expression) -> Optional[Expression]:
        if id(e) in replacement:
            from ..expressions import Alias

            return Alias(col(replacement[id(e)]), e.name())
        return None

    new_proj = [e.transform(rewrite) for e in node.projection]
    return lp.Project(input_node, new_proj)


def default_rule_batches(config) -> List[RuleBatch]:
    return [
        RuleBatch("simplify", [
            rule_drop_trivial_filter,
            rule_merge_filters,
            rule_merge_limits,
            rule_drop_noop_project,
        ]),
        RuleBatch("pushdowns", [
            rule_push_filter_into_scan,
            rule_push_limit_through,
            rule_push_limit_into_scan,
            rule_column_pruning,
        ]),
        RuleBatch("physical-prep", [
            rule_detect_topn,
            rule_extract_windows,
            rule_split_udfs,
        ], max_passes=3),
    ]
