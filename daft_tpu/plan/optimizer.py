"""Rule-based logical optimizer.

Reference parity: src/daft-logical-plan/src/optimization/optimizer.rs:60,309
(RuleBatch fixed-point pass manager) and optimization/rules/*. Rules are
functions plan→plan|None applied bottom-up to fixed point per batch.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..expressions import ColumnRef, Expression, col
from ..expressions.expressions import BinaryOp, Literal
from . import logical as lp

Rule = Callable[[lp.LogicalPlan], Optional[lp.LogicalPlan]]


class RuleBatch:
    def __init__(self, name: str, rules: List[Rule], max_passes: int = 5):
        self.name = name
        self.rules = rules
        self.max_passes = max_passes


class Optimizer:
    def __init__(self, config=None):
        self.config = config
        self.batches = default_rule_batches(config)

    def optimize(self, plan: lp.LogicalPlan) -> lp.LogicalPlan:
        for batch in self.batches:
            for _ in range(batch.max_passes):
                changed = False
                for rule in batch.rules:
                    new = plan.transform_up(_track(rule))
                    if new is not plan:
                        plan = new
                        changed = True
                if not changed:
                    break
            if batch.name == "pushdowns":
                # global projection pushdown after filters have settled
                plan = prune_columns(plan)
                # then cost-based join reordering (top-down so each maximal
                # inner-join chain is reordered exactly once, at its root)
                plan = reorder_joins_global(plan)
        return plan


def _track(rule: Rule) -> Rule:
    def wrapped(node):
        out = rule(node)
        return out

    return wrapped


# ======================================================================================
# Rules
# ======================================================================================


def simplify_expr(e: Expression, schema=None) -> Expression:
    """Algebraic expression simplification (reference: src/daft-algebra
    simplify_expr + the SimplifyExpressions optimizer rule). Conservative,
    null-semantics-preserving rewrites applied bottom-up:

    - literal folding: <lit> op <lit> evaluates at plan time
    - arithmetic identities: x+0, 0+x, x-0, x*1, 1*x, x/1 -> x
    - Kleene boolean identities: TRUE AND e -> e, FALSE AND e -> FALSE,
      FALSE OR e -> e, TRUE OR e -> TRUE, NOT NOT e -> e
    - if_else with a literal predicate picks its branch

    (x*0 is NOT rewritten: nulls must propagate.) With a schema, every rewrite
    is dtype-checked — a replacement that would change the resolved output
    dtype (e.g. int_col / 1 -> int_col, where div promotes to float64) is
    rejected.
    """
    from ..expressions.expressions import IfElse, UnaryOp

    def lit_val(x):
        return x.value if isinstance(x, Literal) else _MISSING

    def is_num(x, v):
        lv = lit_val(x)
        return isinstance(lv, (int, float)) and not isinstance(lv, bool) and lv == v

    def rewrite(node):
        out = _rewrite(node)
        if out is None or schema is None:
            return out
        try:
            if out.to_field(schema).dtype != node.to_field(schema).dtype:
                return None  # rewrite would change the output dtype
        except Exception:  # lint: ignore[broad-except] -- untypeable rewrite: keep the original
            return None
        return out

    def _rewrite(node):
        if isinstance(node, BinaryOp):
            l, r = node.left, node.right
            if isinstance(l, Literal) and isinstance(r, Literal) and node.op not in (
                    "and", "or"):
                folded = _fold_literal_binop(node)
                if folded is not None:
                    return folded
            if node.op == "add":
                if is_num(r, 0):
                    return l
                if is_num(l, 0):
                    return r
            elif node.op == "sub" and is_num(r, 0):
                return l
            elif node.op == "mul":
                if is_num(r, 1):
                    return l
                if is_num(l, 1):
                    return r
            elif node.op == "and":
                if lit_val(l) is True:
                    return r
                if lit_val(r) is True:
                    return l
                if lit_val(l) is False or lit_val(r) is False:
                    return Literal(False)
            elif node.op == "or":
                if lit_val(l) is False:
                    return r
                if lit_val(r) is False:
                    return l
                if lit_val(l) is True or lit_val(r) is True:
                    return Literal(True)
        elif isinstance(node, UnaryOp) and node.op == "not":
            c = node.child
            if isinstance(c, UnaryOp) and c.op == "not":
                return c.child
            if isinstance(lit_val(c), bool):
                return Literal(not c.value)
        elif isinstance(node, IfElse):
            pv = lit_val(node.predicate)
            if pv is True:
                return node.if_true
            if pv is False:
                return node.if_false
            if pv is None and schema is not None:
                # a literal-NULL predicate yields NULL (pc.if_else / device
                # masked-where semantics), not the if_false branch
                try:
                    return Literal(None).cast(node.to_field(schema).dtype)
                except Exception:  # lint: ignore[broad-except] -- uncastable: skip the rewrite
                    return None
        return None

    return e.transform(rewrite)


_MISSING = object()


def _fold_literal_binop(node) -> Optional[Expression]:
    """Evaluate <lit> op <lit> via the host kernels (exact engine semantics)."""
    try:
        from ..core.recordbatch import RecordBatch
        from ..expressions.eval import eval_expression

        dummy = RecordBatch.from_pydict({"__x__": [0]})
        s = eval_expression(dummy, node)
        vals = s.to_pylist()
        if len(vals) != 1:
            return None
        out = Literal(vals[0])
        if out.dtype != s.dtype and not out.dtype.is_null():
            return None  # dtype would change (e.g. int literal for float result)
        return out
    except Exception:  # lint: ignore[broad-except] -- unfoldable expression: keep the original
        return None


def rule_simplify_expressions(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Apply simplify_expr to Filter predicates and Project expressions."""
    if isinstance(node, lp.Filter):
        new = simplify_expr(node.predicate, node.input.schema)
        if repr(new) != repr(node.predicate):
            return lp.Filter(node.input, new)
        return None
    if isinstance(node, lp.Project):
        new_exprs = []
        changed = False
        for e in node.projection:
            ne = simplify_expr(e, node.input.schema)
            if repr(ne) != repr(e):
                changed = True
                if ne.name() != e.name():
                    ne = ne.alias(e.name())  # output names are part of the schema
            new_exprs.append(ne)
        if changed:
            return lp.Project(node.input, new_exprs)
        return None
    return None


def rule_drop_trivial_filter(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Filter(lit(True)) → input (part of SimplifyExpressions in the reference)."""
    if isinstance(node, lp.Filter) and node.predicate.is_literal_true():
        return node.input
    return None


def rule_merge_filters(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Filter(Filter(x, a), b) → Filter(x, a & b), deduping repr-identical
    conjuncts (derived OR-pushdown filters can otherwise stack copies)."""
    if isinstance(node, lp.Filter) and isinstance(node.input, lp.Filter):
        merged = _split_conjuncts(node.input.predicate) + _split_conjuncts(node.predicate)
        seen = set()
        uniq = []
        for c in merged:
            r = repr(c)
            if r not in seen:
                seen.add(r)
                uniq.append(c)
        return lp.Filter(node.input.input, _and_all(uniq))
    return None


def rule_merge_limits(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    if isinstance(node, lp.Limit) and isinstance(node.input, lp.Limit):
        return lp.Limit(node.input.input, min(node.limit, node.input.limit))
    return None


def rule_push_filter_into_scan(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Filter over a ScanSource whose operator can absorb filters → pushdown.

    Reference: rules/push_down_filter.rs. We keep the Filter node (scans may apply
    pushdown filters only approximately, e.g. via zone maps) unless the scan
    promises exact application; translate() checks task.filters_applied.
    """
    if not (isinstance(node, lp.Filter) and isinstance(node.input, lp.ScanSource)):
        return None
    scan = node.input
    if not scan.scan_op.can_absorb_filter():
        return None
    pd = scan.pushdowns
    # idempotence: the Filter node is kept above the scan (pushdown filters may be
    # applied only approximately), so skip once this predicate is already pushed
    if pd.filters is not None and repr(pd.filters) == repr(node.predicate):
        return None
    from ..io.scan import Pushdowns

    if pd.filters is not None:
        new_filters = pd.filters & node.predicate
    else:
        new_filters = node.predicate
    new_scan = lp.ScanSource(scan.scan_op, Pushdowns(pd.columns, new_filters, pd.limit))
    return lp.Filter(new_scan, new_filters)


def _split_conjuncts(e: Expression) -> List[Expression]:
    from ..expressions.expressions import BinaryOp

    if isinstance(e, BinaryOp) and e.op == "and":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _split_disjuncts(e: Expression) -> List[Expression]:
    from ..expressions.expressions import BinaryOp

    if isinstance(e, BinaryOp) and e.op == "or":
        return _split_disjuncts(e.left) + _split_disjuncts(e.right)
    return [e]


def _and_all(exprs: List[Expression]) -> Expression:
    out = exprs[0]
    for e in exprs[1:]:
        out = out & e
    return out


def _or_all(exprs: List[Expression]) -> Expression:
    out = exprs[0]
    for e in exprs[1:]:
        out = out | e
    return out


def _rename_refs(e: Expression, mapping) -> Expression:
    def rewrite(x: Expression) -> Optional[Expression]:
        if isinstance(x, ColumnRef) and x._name in mapping:
            return col(mapping[x._name])
        return None

    return e.transform(rewrite)


def _existing_conjunct_reprs(node: lp.LogicalPlan) -> set:
    """Conjuncts already filtering this subtree (looking through name-preserving
    Projects, which rule_push_filter_through_project may have inserted between
    the join and a previously-derived filter)."""
    out: set = set()
    while True:
        if isinstance(node, lp.Filter):
            out |= {repr(c) for c in _split_conjuncts(node.predicate)}
            node = node.input
        elif isinstance(node, lp.Project):
            node = node.input
        else:
            return out


def rule_cross_join_to_inner(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Filter(CrossJoin) with cross-side equality conjuncts -> inner hash join
    + residual filter (reference: SQL-92 comma-join recovery in join planning).
    Only fires when every extracted key pair has distinct column names, so the
    rewritten join merges nothing and the output schema is unchanged."""
    if not (isinstance(node, lp.Filter) and isinstance(node.input, lp.Join)
            and node.input.how == "cross"):
        return None
    join = node.input
    left_names = set(join.left.schema.column_names())
    _merged, right_rename = join.output_naming()
    right_out_to_src = {right_rename.get(n, n): n
                        for n in join.right.schema.column_names()}

    keys, rest = [], []
    for c in _split_conjuncts(node.predicate):
        if isinstance(c, BinaryOp) and c.op == "eq" \
                and isinstance(c.left, ColumnRef) and isinstance(c.right, ColumnRef):
            ln, rn = c.left._name, c.right._name
            if ln in left_names and rn in right_out_to_src and rn not in left_names:
                keys.append((ln, right_out_to_src[rn]))
                continue
            if rn in left_names and ln in right_out_to_src and ln not in left_names:
                keys.append((rn, right_out_to_src[ln]))
                continue
        rest.append(c)
    if not keys or any(l == r for l, r in keys):
        return None
    inner = lp.Join(join.left, join.right, [col(l) for l, _ in keys],
                    [col(r) for _, r in keys], "inner", join.prefix, join.suffix)
    if set(inner.schema.column_names()) != set(node.input.schema.column_names()):
        return None  # renaming diverged; keep the cross join
    out: lp.LogicalPlan = inner
    if rest:
        pred = rest[0]
        for r in rest[1:]:
            pred = pred & r
        out = lp.Filter(inner, pred)
    return out


def rule_push_filter_through_join(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Filter(Join) → push side-local conjuncts below the join; derive relaxed
    OR-predicates for cross-side disjunctions.

    Reference: rules/push_down_filter.rs (+ its extract-or-predicates step).
    - inner/cross: conjuncts referencing only one side's columns move to that side.
    - left/right joins: only the preserved side accepts pushes (filters on the
      null-extended side would change null-extension semantics).
    - semi/anti: output schema is the left side; all conjuncts push left.
    - A conjunct (A1&B1)|(A2&B2)|… where every disjunct Ai references only one
      side pushes (A1|A2|…) to that side as a *derived* filter — the original
      conjunct stays above the join (classic q19 shape).
    """
    if not (isinstance(node, lp.Filter) and isinstance(node.input, lp.Join)):
        return None
    join = node.input
    if join.how == "outer":
        return None
    left_cols = set(join.left.schema.column_names())
    merged_keys, right_rename = join.output_naming()
    # output-name -> right-side-internal-name, for columns sourced from the right
    right_names = join.right.schema.column_names()
    out_to_right = {}
    for c in right_names:
        if join.how in ("semi", "anti"):
            break
        if c in merged_keys:
            continue
        out_to_right[right_rename.get(c, c)] = c

    push_left = join.how in ("inner", "cross", "left", "semi", "anti")
    push_right = join.how in ("inner", "cross", "right")

    left_push: List[Expression] = []
    right_push: List[Expression] = []
    remaining: List[Expression] = []
    derived_left: List[Expression] = []
    derived_right: List[Expression] = []

    for conj in _split_conjuncts(node.predicate):
        if conj.has_udf():
            remaining.append(conj)
            continue
        refs = set(conj.referenced_columns())
        refs_left = refs <= left_cols
        refs_right = refs <= set(out_to_right)
        if refs_left and push_left:
            left_push.append(conj)
            continue
        if refs_right and push_right:
            right_push.append(_rename_refs(conj, out_to_right))
            continue
        remaining.append(conj)
        # derived OR-predicate extraction (inner/cross only: a derived filter on
        # one side must not affect null-extension of preserved rows)
        if join.how not in ("inner", "cross"):
            continue
        disjuncts = _split_disjuncts(conj)
        if len(disjuncts) < 2:
            continue
        for side, target in (("l", derived_left), ("r", derived_right)):
            per_disjunct = []
            for d in disjuncts:
                side_parts = []
                for p in _split_conjuncts(d):
                    prefs = set(p.referenced_columns())
                    if side == "l" and prefs <= left_cols and not p.has_udf():
                        side_parts.append(p)
                    elif side == "r" and prefs <= set(out_to_right) and not p.has_udf():
                        side_parts.append(_rename_refs(p, out_to_right))
                if not side_parts:
                    per_disjunct = None
                    break
                per_disjunct.append(_and_all(side_parts))
            if per_disjunct:
                target.append(_or_all(per_disjunct))

    # derived filters stay above too, so guard against re-deriving every pass
    left_existing = _existing_conjunct_reprs(join.left)
    right_existing = _existing_conjunct_reprs(join.right)
    derived_left = [e for e in derived_left if repr(e) not in left_existing]
    derived_right = [e for e in derived_right if repr(e) not in right_existing]

    if not (left_push or right_push or derived_left or derived_right):
        return None

    new_left = join.left
    if left_push or derived_left:
        new_left = lp.Filter(new_left, _and_all(left_push + derived_left))
    new_right = join.right
    if right_push or derived_right:
        new_right = lp.Filter(new_right, _and_all(right_push + derived_right))
    new_join = lp.Join(new_left, new_right, join.left_on, join.right_on, join.how,
                       join.prefix, join.suffix, join.strategy, join.null_equals_null)
    if remaining:
        return lp.Filter(new_join, _and_all(remaining))
    return new_join


def rule_push_filter_through_project(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Filter(Project) → Project(Filter) when every referenced projection output
    is a plain column passthrough or alias of a column (reference:
    rules/push_down_filter.rs over Project)."""
    if not (isinstance(node, lp.Filter) and isinstance(node.input, lp.Project)):
        return None
    from ..expressions.expressions import Alias

    proj = node.input
    mapping = {}
    for e in proj.projection:
        inner = e
        while isinstance(inner, Alias):
            inner = inner.child
        if isinstance(inner, ColumnRef):
            mapping[e.name()] = inner._name
    if node.predicate.has_udf():
        return None
    refs = set(node.predicate.referenced_columns())
    if not refs <= set(mapping):
        return None
    pushed = _rename_refs(node.predicate, mapping)
    return lp.Project(lp.Filter(proj.input, pushed), proj.projection)


def rule_push_limit_into_scan(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    if not (isinstance(node, lp.Limit) and isinstance(node.input, lp.ScanSource)):
        return None
    scan = node.input
    pd = scan.pushdowns
    if pd.filters is not None:
        return None  # limit-after-filter can't be pushed below the filter
    if pd.limit is not None and pd.limit <= node.limit:
        return None
    from ..io.scan import Pushdowns

    new_scan = lp.ScanSource(scan.scan_op, Pushdowns(pd.columns, pd.filters, node.limit))
    return lp.Limit(new_scan, node.limit)


def rule_push_limit_through(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Limit commutes with Project (row-preserving)."""
    if isinstance(node, lp.Limit) and isinstance(node.input, lp.Project):
        proj = node.input
        if not any(e.has_udf() for e in proj.projection):
            return lp.Project(lp.Limit(proj.input, node.limit), proj.projection)
    return None


def rule_detect_topn(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Limit(Sort) → TopN (reference: extract TopN)."""
    if isinstance(node, lp.Limit) and isinstance(node.input, lp.Sort):
        s = node.input
        return lp.TopN(s.input, s.sort_by, s.descending, s.nulls_first, node.limit)
    if (isinstance(node, lp.Limit) and isinstance(node.input, lp.Offset)
            and isinstance(node.input.input, lp.Sort)):
        s = node.input.input
        return lp.TopN(s.input, s.sort_by, s.descending, s.nulls_first,
                       node.limit, node.input.offset)
    return None


def _projection_is_passthrough(projection: List[Expression], input_schema) -> bool:
    names = input_schema.column_names()
    if len(projection) != len(names):
        return False
    for e, n in zip(projection, names):
        if not (isinstance(e, ColumnRef) and e._name == n):
            return False
    return True


def rule_drop_noop_project(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    if isinstance(node, lp.Project) and _projection_is_passthrough(node.projection, node.input.schema):
        return node.input
    return None


def rule_column_pruning(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Push column selection into ScanSource when a Project only needs a subset.

    Reference: rules/push_down_projection.rs (materialized as scan pushdown here;
    general projection pushdown through intermediate ops lands with M2).
    """
    if not (isinstance(node, lp.Project) and isinstance(node.input, lp.ScanSource)):
        return None
    scan = node.input
    needed: List[str] = []
    for e in node.projection:
        for c in e.referenced_columns():
            if c not in needed:
                needed.append(c)
    if scan.pushdowns.filters is not None:
        for c in scan.pushdowns.filters.referenced_columns():
            if c not in needed:
                needed.append(c)
    all_cols = scan.scan_op.schema().column_names()
    needed = [c for c in all_cols if c in set(needed)]
    if len(needed) >= len(scan.schema.column_names()):
        return None
    from ..io.scan import Pushdowns

    pd = scan.pushdowns
    new_scan = lp.ScanSource(scan.scan_op, Pushdowns(needed, pd.filters, pd.limit))
    return lp.Project(new_scan, node.projection)


def _ordered_union(*col_lists) -> List[str]:
    out: List[str] = []
    for cols in col_lists:
        for c in cols:
            if c not in out:
                out.append(c)
    return out


def _refs(exprs) -> List[str]:
    out: List[str] = []
    for e in exprs:
        for c in e.referenced_columns():
            if c not in out:
                out.append(c)
    return out


def prune_columns(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    """Global projection pushdown (reference: rules/push_down_projection.rs).

    Walks top-down computing the column set each operator actually needs and
    narrows sources: ScanSource gets a columns pushdown, InMemorySource gets a
    Project wrapper, joins prune both sides (accounting for right-side renames).
    Shrinks every downstream batch — filters, joins and shuffles stop carrying
    dead columns.
    """
    return _prune(plan, None)


def _restrict(needed: Optional[List[str]], schema) -> Optional[List[str]]:
    """Intersect needed with a schema, in schema order; None passes through."""
    if needed is None:
        return None
    names = schema.column_names()
    keep = [c for c in names if c in set(needed)]
    if not keep:  # never prune to zero columns (row counts must survive)
        keep = names[:1]
    return keep


def _prune(node: lp.LogicalPlan, needed: Optional[List[str]]) -> lp.LogicalPlan:
    if isinstance(node, lp.InMemorySource):
        keep = _restrict(needed, node.schema)
        if keep is not None and len(keep) < len(node.schema.column_names()):
            return lp.Project(node, [col(c) for c in keep])
        return node

    if isinstance(node, lp.ScanSource):
        base_cols = node.schema.column_names()
        want = _restrict(
            _ordered_union(
                needed if needed is not None else base_cols,
                _refs([node.pushdowns.filters]) if node.pushdowns.filters is not None else [],
            ),
            node.schema,
        )
        if needed is not None and want is not None and len(want) < len(base_cols):
            from ..io.scan import Pushdowns

            pd = node.pushdowns
            return lp.ScanSource(node.scan_op, Pushdowns(want, pd.filters, pd.limit))
        return node

    if isinstance(node, lp.Project):
        proj = node.projection
        if needed is not None:
            proj = [e for e in proj if e.name() in set(needed)]
            if not proj:
                proj = node.projection[:1]
        child = _prune(node.input, _refs(proj))
        return lp.Project(child, proj)

    if isinstance(node, lp.UDFProject):
        passthrough = node.passthrough
        if needed is not None:
            keep = set(needed)
            passthrough = [e for e in passthrough if e.name() in keep]
        child = _prune(node.input, _ordered_union(_refs([node.udf_expr]), _refs(passthrough)))
        return lp.UDFProject(child, node.udf_expr, passthrough)

    if isinstance(node, lp.Filter):
        child = _prune(node.input, None if needed is None
                       else _ordered_union(needed, _refs([node.predicate])))
        # downstream needs fewer columns than the predicate reads: mark the
        # filter to materialize only those (predicate-only columns are masked
        # over but never gathered into the output)
        keep = None
        if needed is not None:
            names = child.schema.column_names()
            k = [c for c in names if c in set(needed)]
            if not k:
                k = names[:1]
            if len(k) < len(names):
                keep = k
        return lp.Filter(child, node.predicate, keep)

    if isinstance(node, (lp.Limit, lp.Offset, lp.Sample, lp.IntoBatches, lp.IntoPartitions)):
        return node.with_children([_prune(node.input, needed)])

    if isinstance(node, lp.Repartition):
        child_needed = None if needed is None else _ordered_union(needed, _refs(node.by))
        return node.with_children([_prune(node.input, child_needed)])

    if isinstance(node, lp.MonotonicallyIncreasingId):
        child_needed = None if needed is None else [c for c in needed if c != node.column_name]
        return node.with_children([_prune(node.input, child_needed)])

    if isinstance(node, lp.Distinct):
        if node.on is None:
            child_needed = None
        else:
            child_needed = None if needed is None else _ordered_union(needed, _refs(node.on))
        return node.with_children([_prune(node.input, child_needed)])

    if isinstance(node, (lp.Sort, lp.TopN)):
        child_needed = None if needed is None else _ordered_union(needed, _refs(node.sort_by))
        return node.with_children([_prune(node.input, child_needed)])

    if isinstance(node, lp.Aggregate):
        child = _prune(node.input, _ordered_union(_refs(node.groupby), _refs(node.aggregations)))
        return lp.Aggregate(child, node.groupby, node.aggregations)

    if isinstance(node, lp.Explode):
        child_needed = None if needed is None else _ordered_union(needed, _refs(node.to_explode))
        return node.with_children([_prune(node.input, child_needed)])

    if isinstance(node, lp.Concat):
        return node.with_children([_prune(c, needed) for c in node.inputs])

    if isinstance(node, lp.Join):
        left_names = node.left.schema.column_names()
        right_names = node.right.schema.column_names()
        merged_keys, right_rename = node.output_naming()
        if needed is None:
            left_needed = None
            right_needed = None
        else:
            left_needed = _ordered_union(
                [c for c in needed if c in set(left_names)], _refs(node.left_on))
            if node.how in ("anti", "semi"):
                right_needed = _refs(node.right_on)
            else:
                out_to_right = {right_rename.get(c, c): c for c in right_names
                                if c not in merged_keys}
                right_needed = _ordered_union(
                    [out_to_right[c] for c in needed if c in out_to_right],
                    _refs(node.right_on))
        return lp.Join(_prune(node.left, left_needed), _prune(node.right, right_needed),
                       node.left_on, node.right_on, node.how,
                       node.prefix, node.suffix, node.strategy, node.null_equals_null)

    # Window / Pivot / Unpivot / Sink / anything else: conservatively need all
    return node.with_children([_prune(c, None) for c in node.children()])


def _contains_device_udf(expr) -> bool:
    """True when any UDF call inside `expr` is a device Func
    (``on_device=True``) — structural check only, no tier imports."""
    from ..udf.expr import UdfCall

    return any(isinstance(sub, UdfCall) and getattr(sub.func, "on_device", False)
               for sub in expr.walk())


def rule_split_udfs(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Isolate UDF-bearing expressions into their own UDFProject nodes
    (reference: rules/split_udfs.rs) so host UDFs don't break device stage fusion.

    Extracts EVERY UDF expression in one application (stacked UDFProject nodes),
    so isolation doesn't depend on the batch's pass budget; each UDF output gets
    a unique internal name so sibling expressions referencing a same-named input
    column are unaffected.
    """
    if not isinstance(node, lp.Project):
        return None
    udf_exprs = [e for e in node.projection if e.has_udf()]
    if not udf_exprs:
        return None
    if len(node.projection) == len(udf_exprs) == 1 \
            and not _contains_device_udf(udf_exprs[0]):
        # a lone host-UDF projection gains nothing from isolation; a lone
        # DEVICE-UDF projection must still land in a UDFProject node so the
        # device-UDF tier (plan/physical.py DeviceUdfProject) can capture it
        return None
    current = node.input
    projection = list(node.projection)
    for target in udf_exprs:
        out_name = target.name()
        input_cols = current.schema.column_names()
        taken = set(input_cols) | {e.name() for e in projection}
        internal = f"__udf__{out_name}"
        while internal in taken:
            internal = "_" + internal
        passthrough = [col(c) for c in input_cols]
        current = lp.UDFProject(current, target.alias(internal), passthrough)
        projection = [
            col(internal).alias(out_name) if e is target else e for e in projection
        ]
    return lp.Project(current, projection)


def rule_extract_windows(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Pull WindowExpr nodes out of projections into Window plan nodes
    (reference: rules/extract_window_function.rs)."""
    if not isinstance(node, lp.Project):
        return None
    from ..expressions.expressions import WindowExpr

    found: List = []
    seen_ids = set()
    for e in node.projection:
        for sub in e.walk():
            if isinstance(sub, WindowExpr) and id(sub) not in seen_ids:
                seen_ids.add(id(sub))
                found.append(sub)
    if not found:
        return None

    # group by spec *content* so equal-but-distinct Window() objects share one
    # sort+segment pass, and dedupe identical window computations within a spec
    by_spec = {}
    replacement = {}
    for w in found:
        spec_key = repr(w.spec)
        spec, ws = by_spec.setdefault(spec_key, (w.spec, {}))
        expr_key = (w.func, repr(w.child), repr(sorted(w.params.items(), key=str)))
        if expr_key not in ws:
            ws[expr_key] = (f"__window_{len(replacement)}", w)
        replacement[id(w)] = ws[expr_key][0]

    input_node = node.input
    for spec, ws in by_spec.values():
        named = [w.alias(internal) for internal, w in ws.values()]
        input_node = lp.Window(input_node, named, spec)

    def rewrite(e: Expression) -> Optional[Expression]:
        if id(e) in replacement:
            from ..expressions import Alias

            return Alias(col(replacement[id(e)]), e.name())
        return None

    new_proj = [e.transform(rewrite) for e in node.projection]
    return lp.Project(input_node, new_proj)


def reorder_joins_global(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    """Top-down driver: reorder each maximal inner-join chain at its root, then
    recurse into the chain's relation subtrees (nested chains under aggregates,
    filters, etc. each get their own reorder)."""
    if _plain_inner_join(plan):
        rewritten = _reorder_join_chain(plan)
        target = rewritten if rewritten is not None else plan

        def recurse_spine(n):
            # walk the join spine; recurse into relation leaves only
            if _plain_inner_join(n):
                kids = [recurse_spine(c) for c in n.children()]
                if all(k is o for k, o in zip(kids, n.children())):
                    return n
                return n.with_children(kids)
            return reorder_joins_global(n)

        return recurse_spine(target) if not isinstance(target, lp.Project) \
            else target.with_children([recurse_spine(target.input)])
    children = plan.children()
    if not children:
        return plan
    new_children = [reorder_joins_global(c) for c in children]
    if all(n is o for n, o in zip(new_children, children)):
        return plan
    return plan.with_children(new_children)


def _reorder_join_chain(node: lp.LogicalPlan) -> Optional[lp.LogicalPlan]:
    """Greedy cost-based join reordering (reference:
    optimization/rules/reorder_joins/ — greedy smallest-first over the
    stats.py estimates instead of brute-force enumeration).

    Applies to maximal chains of plain inner equi-joins (no explicit strategy,
    no prefix/suffix renames, bare-column keys): start from the smallest
    estimated relation, repeatedly join the smallest connected relation.
    Filters pushed into scans (the pushdown batch runs first) make the
    estimates selectivity-aware. The rewritten tree is wrapped in a Project
    restoring the original column order; fires only when the order actually
    changes (stable under re-application)."""
    from .stats import estimate_rows

    if not _plain_inner_join(node):
        return None
    rels: List[lp.LogicalPlan] = []
    conds: List[tuple] = []  # (name_a, name_b)

    def flatten(j) -> bool:
        for lo, ro in zip(j.left_on, j.right_on):
            a, b = _bare_name(lo), _bare_name(ro)
            if a is None or b is None:
                return False
            conds.append((a, b))
        for side in (j.left, j.right):
            if _plain_inner_join(side):
                if not flatten(side):
                    return False
            else:
                rels.append(side)
        return True

    if not flatten(node) or len(rels) < 3:
        return None

    # column name -> owning relations. Same-named join keys (df.join(on="k"))
    # legitimately live in several relations and merge at each join; any OTHER
    # ambiguity bails (can't attribute the condition to a relation).
    owners = {}
    for i, r in enumerate(rels):
        for name in r.schema.column_names():
            owners.setdefault(name, []).append(i)
    for a, b in conds:
        if a not in owners or b not in owners:
            return None
        if a != b and (len(owners[a]) != 1 or len(owners[b]) != 1):
            return None
    # a name living in several relations is only safe when it is a same-name
    # join key (inner-join merge makes the values equal, so any order binds the
    # same data); shared NON-key names would silently swap sources on reorder
    for name, ow in owners.items():
        if len(ow) > 1 and not any(a == b == name for a, b in conds):
            return None

    from .stats import estimate_distinct, estimate_join_result

    big = float("inf")
    est = []
    for r in rels:
        e = estimate_rows(r)
        if e is None:
            return None
        est.append(e)
    # Selinger V(R, a) for every join-key column per owning relation
    v: dict = {}
    for a, b in conds:
        for name in (a, b):
            for i in owners[name]:
                if (i, name) not in v:
                    v[(i, name)] = estimate_distinct(rels[i], name)

    def rel_cols(i):
        return set(rels[i].schema.column_names())

    def join_est(cur_rows, cur_v, i):
        """Estimated result of joining relation i into the current set, using
        every applicable condition (independence assumption)."""
        out = cur_rows * est[i]
        found = False
        rc = rel_cols(i)
        for a, b in conds:
            sides = None
            if a in cur_v and b in rc:
                sides = (cur_v.get(a), v.get((i, b)))
            elif b in cur_v and a in rc:
                sides = (cur_v.get(b), v.get((i, a)))
            if sides is None:
                continue
            found = True
            vl = sides[0] if sides[0] is not None else cur_rows
            vr = sides[1] if sides[1] is not None else est[i]
            out = out / max(vl, vr, 1.0)
        if not found:
            return None  # not connected
        return max(out, 1.0)

    def simulate(order):
        """Cost of a join order: each step pays its INPUT sizes (hash build +
        probe are linear in rows processed) plus its intermediate result. The
        final result is the query output — identical for every valid order —
        so only its inputs count."""
        cur_rows = est[order[0]]
        cur_v = {name: v.get((order[0], name))
                 for (i, name) in v if i == order[0]}
        cost = 0.0
        for step, i in enumerate(order[1:]):
            res = join_est(cur_rows, cur_v, i)
            if res is None:
                return None, None
            cost += cur_rows + est[i]
            if step < len(order) - 2:
                cost += res
            for (j, name), val in v.items():
                if j == i:
                    cur_v[name] = val
            # joining shrinks per-column distincts to at most the result rows
            cur_v = {n: (min(x, res) if x is not None else None)
                     for n, x in cur_v.items()}
            cur_rows = res
        return cost, cur_rows

    # greedy: start from the smallest relation, repeatedly add the connected
    # relation with the smallest step cost (its own size + the join result —
    # pulling a huge relation in early is paid for, not hidden)
    order = [min(range(len(rels)), key=lambda i: (est[i], i))]
    placed = {order[0]}
    cur_rows = est[order[0]]
    cur_v = {name: v.get((order[0], name)) for (i, name) in v if i == order[0]}
    while len(placed) < len(rels):
        best = None
        for i in range(len(rels)):
            if i in placed:
                continue
            res = join_est(cur_rows, cur_v, i)
            if res is None:
                continue
            step_cost = est[i] + res
            if best is None or step_cost < best[0] or (step_cost == best[0] and i < best[1]):
                best = (step_cost, i, res)
        if best is None:
            return None  # disconnected components would need a cross join
        _cost, nxt, res = best
        order.append(nxt)
        placed.add(nxt)
        for (j, name), val in v.items():
            if j == nxt:
                cur_v[name] = val
        cur_v = {n: (min(x, res) if x is not None else None) for n, x in cur_v.items()}
        cur_rows = res

    current_order = list(range(len(rels)))  # flatten() emits left-deep order
    if order == current_order:
        return None
    # only rewrite on a clear predicted win: estimates are rough, and
    # hand-ordered queries must never be pessimized by a coin-flip estimate
    orig_cost, _ = simulate(current_order)
    new_cost, _ = simulate(order)
    if orig_cost is None or new_cost is None or new_cost >= 0.5 * orig_cost:
        return None

    cur = rels[order[0]]
    have = set(cur.schema.column_names())
    for i in order[1:]:
        r = rels[i]
        rcols = set(r.schema.column_names())
        left_on, right_on = [], []
        for a, b in conds:
            if a in have and b in rcols:
                left_on.append(col(a))
                right_on.append(col(b))
            elif b in have and a in rcols:
                left_on.append(col(b))
                right_on.append(col(a))
        cur = lp.Join(cur, r, left_on, right_on, "inner")
        have |= rcols
    if set(cur.schema.column_names()) != set(node.schema.column_names()):
        return None  # merged-key set changed; keep the original plan
    return lp.Project(cur, [col(f.name) for f in node.schema])


def _plain_inner_join(n) -> bool:
    # null_equals_null joins are excluded: the reordered chain is rebuilt with
    # default join semantics, which would silently flip nulls-match behavior
    return (isinstance(n, lp.Join) and n.how == "inner" and n.strategy is None
            and n.prefix is None and n.suffix is None and not n.null_equals_null)


def _bare_name(e: Expression) -> Optional[str]:
    from ..expressions.expressions import Alias

    node = e.child if isinstance(e, Alias) else e
    return node._name if isinstance(node, ColumnRef) else None


def default_rule_batches(config) -> List[RuleBatch]:
    return [
        RuleBatch("simplify", [
            rule_simplify_expressions,
            rule_drop_trivial_filter,
            rule_merge_filters,
            rule_merge_limits,
            rule_drop_noop_project,
        ]),
        RuleBatch("pushdowns", [
            rule_cross_join_to_inner,
            rule_push_filter_through_join,
            rule_push_filter_through_project,
            rule_merge_filters,
            rule_push_filter_into_scan,
            rule_push_limit_through,
            rule_push_limit_into_scan,
            rule_column_pruning,
        ]),
        RuleBatch("physical-prep", [
            rule_detect_topn,
            rule_extract_windows,
            rule_split_udfs,
        ], max_passes=3),
    ]
