"""Aggregation splitting: partial (per-morsel/per-shard) + final (combine) phases.

Reference parity: src/daft-local-plan/src/translate.rs agg splitting and
src/daft-physical-plan two-stage aggregation. The same decomposition drives
thread-parallel partial aggregation on host, psum-combined shard aggregation on
the TPU mesh (parallel/distributed.py), and distributed partition aggregation.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..expressions import AggExpr, Alias, ColumnRef, Expression, col
from ..expressions.expressions import Literal


def _unalias(e: Expression) -> Tuple[Expression, str]:
    name = e.name()
    while isinstance(e, Alias):
        e = e.child
    return e, name


class AggSplit:
    """partial: AggExprs evaluated per input chunk; final: AggExprs over the
    concatenated partials; projection: final output expressions (one per input
    agg, aliased to the original output name)."""

    def __init__(self, partial: List[Expression], final: List[Expression],
                 projection: List[Expression]):
        self.partial = partial
        self.final = final
        self.projection = projection


def split_aggs(aggs: List[Expression]) -> Optional[AggSplit]:
    """Decompose aggregations into partial+final, or None if any agg can't split
    (count_distinct/approx_count_distinct need full value sets)."""
    partial: List[Expression] = []
    final: List[Expression] = []
    projection: List[Expression] = []
    counter = [0]
    seen: dict = {}  # (repr(partial agg), final op) -> column name — dedupe shared partials

    def fresh(base: str) -> str:
        counter[0] += 1
        return f"__p{counter[0]}_{base}"

    def add(p_expr: Expression, f_op: str, f_params=None) -> str:
        """Register a partial agg + its final combine; returns the final column name."""
        key = (repr(p_expr), f_op, repr(sorted((f_params or {}).items())))
        if key in seen:
            return seen[key]
        name = fresh(p_expr.name() if not isinstance(p_expr, Literal) else "lit")
        partial.append(p_expr.alias(name))
        final.append(AggExpr(f_op, col(name), f_params or {}).alias(name))
        seen[key] = name
        return name

    for e in aggs:
        inner, out_name = _unalias(e)
        if not isinstance(inner, AggExpr):
            return None
        op = inner.op
        child = inner.child
        if op == "sum":
            n = add(AggExpr("sum", child), "sum")
            projection.append(col(n).alias(out_name))
        elif op == "count":
            n = add(AggExpr("count", child, dict(inner.params)), "sum")
            from ..datatype import DataType

            projection.append(col(n).cast(DataType.uint64()).alias(out_name))
        elif op in ("min", "max", "any_value", "bool_and", "bool_or"):
            n = add(AggExpr(op, child, dict(inner.params)), op, dict(inner.params))
            projection.append(col(n).alias(out_name))
        elif op == "mean":
            s = add(AggExpr("sum", child), "sum")
            c = add(AggExpr("count", child), "sum")
            projection.append((col(s) / col(c)).alias(out_name))
        elif op in ("stddev", "var"):
            ddof = inner.params.get("ddof", 0)
            s = add(AggExpr("sum", child), "sum")
            q = add(AggExpr("sum", child * child), "sum")
            c = add(AggExpr("count", child), "sum")
            mean = col(s) / col(c)
            # clamp: float error can push E[x²]−E[x]² slightly negative (must match
            # the one-phase kernel's np.maximum(var, 0.0))
            var = ((col(q) / col(c)) - mean * mean).clip(min=0.0)
            if ddof:
                from ..expressions import lit

                # groups with count <= ddof have no defined sample variance: NULL,
                # not inf/NaN (matches the one-phase kernel)
                var = (col(c) > ddof).if_else(
                    var * col(c) / (col(c) - ddof), lit(None)
                )
            expr = var.sqrt() if op == "stddev" else var
            projection.append(expr.alias(out_name))
        elif op == "skew":
            from ..expressions import lit

            s = add(AggExpr("sum", child), "sum")
            q = add(AggExpr("sum", child * child), "sum")
            cu = add(AggExpr("sum", child * child * child), "sum")
            c = add(AggExpr("count", child), "sum")
            m = col(s) / col(c)
            var = ((col(q) / col(c)) - m * m).clip(min=0.0)
            sd = var.sqrt()
            m3 = (col(cu) / col(c)) - 3 * m * (col(q) / col(c)) + 2 * m * m * m
            # zero variance → undefined skew (one-phase kernel nulls it)
            projection.append((sd > 0).if_else(m3 / (sd ** 3), lit(None)).alias(out_name))
        elif op == "product":
            n = add(AggExpr("product", child), "product")
            projection.append(col(n).alias(out_name))
        elif op == "list":
            n = add(AggExpr("list", child), "concat")
            projection.append(col(n).alias(out_name))
        elif op == "concat":
            n = add(AggExpr("concat", child), "concat")
            projection.append(col(n).alias(out_name))
        else:
            # count_distinct / approx_count_distinct: need full sets
            return None
    return AggSplit(partial, final, projection)
