"""Physical plan + logical→physical translation.

Reference parity: src/daft-local-plan/src/plan.rs:61-115 (LocalPhysicalPlan enum)
and src/daft-local-plan/src/translate.rs:21. Physical nodes are what the executor
interprets; translation picks join strategies and lowers logical ops.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ..expressions import Expression
from ..schema import Schema
from . import logical as lp


class PhysicalPlan:
    def __init__(self) -> None:
        self.schema: Schema = None  # type: ignore[assignment]

    def children(self) -> List["PhysicalPlan"]:
        return []

    def name(self) -> str:
        return type(self).__name__

    def display(self) -> str:
        lines: List[str] = []

        def rec(node, depth):
            lines.append("  " * depth + "* " + node.name())
            for c in node.children():
                rec(c, depth + 1)

        rec(self, 0)
        return "\n".join(lines)

    def walk(self):
        yield self
        for c in self.children():
            yield from c.walk()


class _Unary(PhysicalPlan):
    def __init__(self, input: PhysicalPlan, schema: Schema):
        super().__init__()
        self.input = input
        self.schema = schema

    def children(self):
        return [self.input]


class InMemoryScan(PhysicalPlan):
    def __init__(self, partitions: List[Any], schema: Schema):
        super().__init__()
        self.partitions = partitions
        self.schema = schema


class TaskScan(PhysicalPlan):
    """Scan over materialized ScanTasks (post-MaterializeScans)."""

    def __init__(self, tasks: List[Any], schema: Schema,
                 post_filter: Optional[Expression], post_limit: Optional[int]):
        super().__init__()
        self.tasks = tasks
        self.schema = schema
        self.post_filter = post_filter
        self.post_limit = post_limit


class StreamingScan(TaskScan):
    """Out-of-core scan: tasks arrive pre-split/merged toward
    ``scan_split_bytes`` (row-group splits in io/parquet.py, small-file
    merging in io/scan.py) and the executor streams morsels incrementally
    under the host memory ledger (execution/executor.py _streaming_scan) —
    a source is never materialized whole, and a fast scan paces itself
    against memory pressure from downstream spilling operators. Subclasses
    TaskScan so the distributed planner's task partitioning and every
    isinstance gate keep working unchanged."""

    def name(self) -> str:
        return f"StreamingScan({len(self.tasks)} tasks)"


class Project(_Unary):
    def __init__(self, input: PhysicalPlan, projection: List[Expression], schema: Schema):
        super().__init__(input, schema)
        self.projection = projection


class UDFProject(_Unary):
    def __init__(self, input: PhysicalPlan, udf_expr: Expression, passthrough: List[Expression], schema: Schema):
        super().__init__(input, schema)
        self.udf_expr = udf_expr
        self.passthrough = passthrough


class DeviceUdfProject(_Unary):
    """A UDFProject whose UDF is a jax-traceable device Func
    (``@daft_tpu.func(on_device=True)``) — eligible for the device-UDF tier
    (ops/udf_stage.py): weights resident in HBM via the residency manager,
    morsels coalesced into super-batches, one compiled dispatch per
    super-batch, and fusion into a downstream device agg stage with no
    intermediate d2h. The executor decides device vs host per run (cost
    model / backend / config); the host fallback is the plain batch-UDF
    evaluation with identical semantics."""

    def __init__(self, input: PhysicalPlan, udf_expr: Expression,
                 passthrough: List[Expression], schema: Schema):
        super().__init__(input, schema)
        self.udf_expr = udf_expr
        self.passthrough = passthrough

    def name(self) -> str:
        return f"DeviceUdfProject({self.udf_expr.name()})"


def device_udf_call(expr: Expression):
    """The UdfCall at the root of `expr` (aliases unwrapped) when it is a
    kwarg-free device Func call — the shape the device-UDF tier lowers.
    None otherwise. Pure structural check: imports nothing from the tier, so
    host-UDF-only plans keep the zero-overhead contract."""
    from ..expressions.expressions import Alias

    e = expr
    while isinstance(e, Alias):
        e = e.child
    func = getattr(e, "func", None)
    if func is None or not getattr(func, "on_device", False):
        return None
    if getattr(e, "kwargs", None):
        return None  # kwargs don't cross the array contract
    if not getattr(e, "args", None):
        return None
    return e


class PhysFilter(_Unary):
    def __init__(self, input: PhysicalPlan, predicate: Expression, schema: Schema,
                 keep=None):
        super().__init__(input, schema)
        self.predicate = predicate
        self.keep = keep  # output-column subset (late materialization)


class PhysLimit(_Unary):
    def __init__(self, input: PhysicalPlan, limit: int, offset: int, schema: Schema):
        super().__init__(input, schema)
        self.limit = limit
        self.offset = offset


class PhysExplode(_Unary):
    def __init__(self, input: PhysicalPlan, to_explode: List[Expression], schema: Schema):
        super().__init__(input, schema)
        self.to_explode = to_explode


class PhysUnpivot(_Unary):
    def __init__(self, input: PhysicalPlan, ids, values, variable_name, value_name, schema: Schema):
        super().__init__(input, schema)
        self.ids = ids
        self.values = values
        self.variable_name = variable_name
        self.value_name = value_name


class PhysSample(_Unary):
    def __init__(self, input: PhysicalPlan, fraction: float, with_replacement: bool,
                 seed: Optional[int], schema: Schema):
        super().__init__(input, schema)
        self.fraction = fraction
        self.with_replacement = with_replacement
        self.seed = seed


class PhysMonotonicId(_Unary):
    def __init__(self, input: PhysicalPlan, column_name: str, schema: Schema):
        super().__init__(input, schema)
        self.column_name = column_name


class PhysSort(_Unary):
    def __init__(self, input: PhysicalPlan, sort_by, descending, nulls_first, schema: Schema):
        super().__init__(input, schema)
        self.sort_by = sort_by
        self.descending = descending
        self.nulls_first = nulls_first


class PhysTopN(_Unary):
    def __init__(self, input: PhysicalPlan, sort_by, descending, nulls_first, limit, offset, schema: Schema):
        super().__init__(input, schema)
        self.sort_by = sort_by
        self.descending = descending
        self.nulls_first = nulls_first
        self.limit = limit
        self.offset = offset


class UngroupedAggregate(_Unary):
    def __init__(self, input: PhysicalPlan, aggregations: List[Expression], schema: Schema):
        super().__init__(input, schema)
        self.aggregations = aggregations


class HashAggregate(_Unary):
    def __init__(self, input: PhysicalPlan, groupby: List[Expression],
                 aggregations: List[Expression], schema: Schema):
        super().__init__(input, schema)
        self.groupby = groupby
        self.aggregations = aggregations


class PhysMapGroups(_Unary):
    def __init__(self, input: PhysicalPlan, groupby: List[Expression],
                 udf_expr: Expression, schema: Schema):
        super().__init__(input, schema)
        self.groupby = groupby
        self.udf_expr = udf_expr


class DeviceFilterAgg(_Unary):
    """Fused (optional filter)+ungrouped-agg stage eligible for the JAX device.

    The executor decides device vs host per run (config device_mode/min-rows);
    host fallback has identical semantics. Reference wiring point:
    src/daft-local-execution/src/pipeline.rs:358 operator selection.
    """

    def __init__(self, input: PhysicalPlan, predicate: Optional[Expression],
                 aggregations: List[Expression], schema: Schema,
                 region_ops=None):
        super().__init__(input, schema)
        self.predicate = predicate
        self.aggregations = aggregations
        # source-first fused-op chain from the region capture, e.g.
        # ("filter", "project", "agg") — attribution + EXPLAIN only; the
        # fused semantics live in predicate/aggregations themselves.
        self.region_ops = tuple(region_ops) if region_ops else None

    def name(self) -> str:
        if self.region_ops and len(self.region_ops) > 2:
            return f"DeviceFilterAgg[{'+'.join(self.region_ops)}]"
        return "DeviceFilterAgg"


class DeviceJoinAgg(PhysicalPlan):
    """Star-schema join + aggregate fused for the device (ops/device_join.py):
    the fact side streams; each dim materializes once per run and joins as a
    device gather through static per-row indices; the aggregation rides the
    MXU segment-reduction stages. `host_plan` is the untouched translation of
    the same logical subtree — the executor's fallback (config off, runtime
    DeviceFallback, or cost model says host)."""

    def __init__(self, fact: PhysicalPlan, dim_plans, spec, host_plan: PhysicalPlan,
                 schema: Schema):
        super().__init__()
        self.fact = fact
        self.dim_plans = dim_plans  # [(name, PhysicalPlan)] base dims, parents first
        self.spec = spec            # ops.device_join.JoinAggSpec
        self.host_plan = host_plan
        self.schema = schema

    def children(self):
        return [self.fact] + [p for _n, p in self.dim_plans]

    def name(self) -> str:
        return f"DeviceJoinAgg({len(self.dim_plans)} dims)"


class DeviceJoinTopN(PhysicalPlan):
    """Star join + grouped aggregate + ORDER BY + LIMIT fused for the device
    (ops/device_join.py DeviceJoinTopNRun): group tables stay on device; a
    multi-key lax.sort picks the K winners and only K rows are fetched.
    `host_plan` is the untouched translation of the same TopN subtree."""

    def __init__(self, fact: PhysicalPlan, dim_plans, spec, topn, out_map,
                 host_plan: PhysicalPlan, schema: Schema):
        super().__init__()
        self.fact = fact
        self.dim_plans = dim_plans
        self.spec = spec            # ops.device_join.JoinAggSpec
        self.topn = topn            # ops.device_join.TopNSpec
        self.out_map = out_map      # [(kind, index)] per output column
        self.host_plan = host_plan
        self.schema = schema

    def children(self):
        return [self.fact] + [p for _n, p in self.dim_plans]

    def name(self) -> str:
        return f"DeviceJoinTopN({len(self.dim_plans)} dims, k={self.topn.limit})"


class DeviceGroupedAgg(_Unary):
    """Fused (optional filter)+grouped-agg stage eligible for the JAX device.

    Keys factorize on host (any dtype); value reductions segment-reduce on
    device. Executor decides device vs host per run.
    """

    def __init__(self, input: PhysicalPlan, predicate: Optional[Expression],
                 groupby: List[Expression], aggregations: List[Expression], schema: Schema,
                 region_ops=None):
        super().__init__(input, schema)
        self.predicate = predicate
        self.groupby = groupby
        self.aggregations = aggregations
        self.region_ops = tuple(region_ops) if region_ops else None

    def name(self) -> str:
        if self.region_ops and len(self.region_ops) > 2:
            return f"DeviceGroupedAgg[{'+'.join(self.region_ops)}]"
        return "DeviceGroupedAgg"


class Dedup(_Unary):
    def __init__(self, input: PhysicalPlan, on: Optional[List[Expression]], schema: Schema):
        super().__init__(input, schema)
        self.on = on


class PhysPivot(_Unary):
    def __init__(self, input: PhysicalPlan, groupby, pivot_col, value_col, agg_op, names, schema: Schema):
        super().__init__(input, schema)
        self.groupby = groupby
        self.pivot_col = pivot_col
        self.value_col = value_col
        self.agg_op = agg_op
        self.names = names


class PhysWindow(_Unary):
    def __init__(self, input: PhysicalPlan, window_exprs, spec, schema: Schema):
        super().__init__(input, schema)
        self.window_exprs = window_exprs
        self.spec = spec


class PhysConcat(PhysicalPlan):
    def __init__(self, inputs: List[PhysicalPlan], schema: Schema):
        super().__init__()
        self.inputs = inputs
        self.schema = schema

    def children(self):
        return self.inputs


class HashJoin(PhysicalPlan):
    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, left_on, right_on, how,
                 merged_keys, right_rename, schema: Schema, null_equals_null: bool = False,
                 strategy: Optional[str] = None):
        super().__init__()
        self.left = left
        self.right = right
        self.left_on = left_on
        self.right_on = right_on
        self.how = how
        self.merged_keys = merged_keys
        self.right_rename = right_rename
        self.schema = schema
        self.null_equals_null = null_equals_null
        # None/'hash' = probe-table join; 'sort_merge' = order-preserving
        # encode + sorted merge (executor algorithm switch)
        self.strategy = strategy

    def children(self):
        return [self.left, self.right]


class CrossJoin(PhysicalPlan):
    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, right_rename, schema: Schema):
        super().__init__()
        self.left = left
        self.right = right
        self.right_rename = right_rename
        self.schema = schema

    def children(self):
        return [self.left, self.right]


class PhysRepartition(_Unary):
    def __init__(self, input: PhysicalPlan, num_partitions, scheme, by, schema: Schema):
        super().__init__(input, schema)
        self.num_partitions = num_partitions
        self.scheme = scheme
        self.by = by


class PhysIntoBatches(_Unary):
    def __init__(self, input: PhysicalPlan, batch_size: int, schema: Schema):
        super().__init__(input, schema)
        self.batch_size = batch_size


class PhysWrite(_Unary):
    def __init__(self, input: PhysicalPlan, info: Any, schema: Schema):
        super().__init__(input, schema)
        self.info = info


class ShuffleWrite(_Unary):
    """Terminal node of a distributed map task: hash-partition the input stream
    and persist per-partition Arrow IPC files to the shuffle directory
    (reference: src/daft-shuffles/src/shuffle_cache.rs:39 InProgressShuffleCache).
    Yields nothing; consumers use ShuffleRead."""

    def __init__(self, input: PhysicalPlan, shuffle_id: str, map_id: int,
                 num_partitions: int, by: List[Expression], shuffle_dir: str,
                 schema: Schema):
        super().__init__(input, schema)
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.num_partitions = num_partitions
        self.by = by
        self.shuffle_dir = shuffle_dir


class ShuffleRead(PhysicalPlan):
    """Leaf of a distributed reduce task: stream every map's IPC file for one
    shuffle partition (reference: daft-shuffles flight client do_get). With
    `fetch_endpoints` set, files come over the authenticated fetch-server
    sockets instead of the local filesystem (multi-host topology)."""

    def __init__(self, shuffle_id: str, partition_idx: int, shuffle_dir: str,
                 schema: Schema, fetch_endpoints=None, expected_maps=None):
        super().__init__()
        self.shuffle_id = shuffle_id
        self.partition_idx = partition_idx
        self.shuffle_dir = shuffle_dir
        self.schema = schema
        self.fetch_endpoints = fetch_endpoints  # [(host, port, authkey_hex)]
        # map ids the driver's lineage says wrote rows for THIS partition
        # (distributed/planner.py derives them from TaskResult.map_outputs).
        # Readers verify the files exist and raise ShuffleDataLost naming the
        # missing ids — a dead worker's lost outputs become a recoverable
        # event instead of a silently-short reduce input. None = no check
        # (legacy dirs, direct callers).
        self.expected_maps = tuple(expected_maps) if expected_maps else None


# ======================================================================================
# Translation
# ======================================================================================


def _translate_agg_host(plan, config) -> PhysicalPlan:
    """Translate an Aggregate subtree with plain host operators (the fallback
    plan carried by DeviceJoinAgg)."""
    child = translate(plan.input, config)
    if plan.groupby:
        return HashAggregate(child, plan.groupby, plan.aggregations, plan.schema)
    return UngroupedAggregate(child, plan.aggregations, plan.schema)


def translate(plan: lp.LogicalPlan, config: Any = None) -> PhysicalPlan:
    """Lower an (optimized) logical plan to a physical plan."""
    if isinstance(plan, lp.InMemorySource):
        return InMemoryScan(plan.partitions, plan.schema)

    if isinstance(plan, lp.ScanSource):
        tasks = plan.scan_op.to_scan_tasks(plan.pushdowns)
        from ..config import execution_config

        cfg = config or execution_config()
        target = getattr(cfg, "scan_split_bytes", 0)
        if target and len(tasks) > 1:
            from ..io.scan import merge_small_tasks

            tasks = merge_small_tasks(tasks, target)
        post_filter = None
        post_limit = plan.pushdowns.limit
        if plan.pushdowns.filters is not None:
            if not all(t.filters_applied for t in tasks):
                post_filter = plan.pushdowns.filters
        if post_limit is not None and all(t.limit_applied for t in tasks):
            # limit fully absorbed per-task; still cap globally
            pass
        return StreamingScan(tasks, plan.schema, post_filter, post_limit)

    if isinstance(plan, lp.Project):
        return Project(translate(plan.input, config), plan.projection, plan.schema)

    if isinstance(plan, lp.UDFProject):
        from ..config import execution_config

        cfg = config or execution_config()
        if getattr(cfg, "device_mode", "off") != "off" \
                and device_udf_call(plan.udf_expr) is not None:
            # device-UDF tier capture; the executor re-checks mode/cost at
            # run time and falls back to the plain UDF path loudly
            return DeviceUdfProject(translate(plan.input, config), plan.udf_expr,
                                    plan.passthrough, plan.schema)
        return UDFProject(translate(plan.input, config), plan.udf_expr, plan.passthrough, plan.schema)

    if isinstance(plan, lp.Filter):
        return PhysFilter(translate(plan.input, config), plan.predicate, plan.schema,
                          plan.keep)

    if isinstance(plan, lp.Limit):
        return PhysLimit(translate(plan.input, config), plan.limit, 0, plan.schema)

    if isinstance(plan, lp.Offset):
        # standalone offset = skip n rows
        return PhysLimit(translate(plan.input, config), -1, plan.offset, plan.schema)

    if isinstance(plan, lp.Explode):
        return PhysExplode(translate(plan.input, config), plan.to_explode, plan.schema)

    if isinstance(plan, lp.Unpivot):
        return PhysUnpivot(translate(plan.input, config), plan.ids, plan.values,
                           plan.variable_name, plan.value_name, plan.schema)

    if isinstance(plan, lp.Sample):
        return PhysSample(translate(plan.input, config), plan.fraction, plan.with_replacement,
                          plan.seed, plan.schema)

    if isinstance(plan, lp.MonotonicallyIncreasingId):
        return PhysMonotonicId(translate(plan.input, config), plan.column_name, plan.schema)

    if isinstance(plan, lp.Sort):
        return PhysSort(translate(plan.input, config), plan.sort_by, plan.descending,
                        plan.nulls_first, plan.schema)

    if isinstance(plan, lp.TopN):
        from ..config import execution_config

        cfg = config or execution_config()
        if getattr(cfg, "device_mode", "off") != "off":
            from ..ops import counters
            from ..ops.device_join import try_capture_join_topn

            try:
                cap3 = try_capture_join_topn(plan)
            except Exception:
                # capture must never break planning, but a capture BUG must
                # not silently cost every query its device tier either
                counters.reject("capture", "join TopN capture raised")
                cap3 = None
            if cap3 is not None:
                jspec, topn, out_map = cap3
                host = PhysTopN(translate(plan.input, config), plan.sort_by,
                                plan.descending, plan.nulls_first, plan.limit,
                                plan.offset, plan.schema)
                return DeviceJoinTopN(
                    translate(jspec.fact, config),
                    [(d.name, translate(d.base, config)) for d in jspec.dims],
                    jspec, topn, out_map, host, plan.schema)
        return PhysTopN(translate(plan.input, config), plan.sort_by, plan.descending,
                        plan.nulls_first, plan.limit, plan.offset, plan.schema)

    if isinstance(plan, lp.Aggregate):
        # Device-stage fusion: Aggregate(+optional Filter) whose expressions are
        # device-evaluable lowers to a fused Device*Agg node — and when the
        # input is a star-shaped inner-join tree, to a DeviceJoinAgg gather
        # program; the executor picks device vs host at runtime.
        from ..config import execution_config

        cfg = config or execution_config()
        if getattr(cfg, "device_mode", "off") != "off":
            from ..ops import counters
            from ..ops.device_join import try_capture_join_agg

            try:
                jspec = try_capture_join_agg(plan)
            except Exception:
                # same contract as the TopN capture above: degrade AND count
                counters.reject("capture", "join agg capture raised")
                jspec = None
            if jspec is not None:
                host = _translate_agg_host(plan, config)
                return DeviceJoinAgg(
                    translate(jspec.fact, config),
                    [(d.name, translate(d.base, config)) for d in jspec.dims],
                    jspec, host, plan.schema)
            # Whole-stage fused-region capture: collapse the maximal
            # Filter/Project chain under the aggregate into composed
            # expressions over the chain's base, then qualify candidates
            # most-fused-first against the device stage builders. The last
            # candidate reproduces the legacy one-Filter peel, so nothing
            # that fused before stops fusing.
            if getattr(cfg, "region_mode", "on") != "off":
                from ..ops.region import agg_region_candidates

                try:
                    cands = agg_region_candidates(plan)
                except Exception:
                    counters.reject("capture", "fused region capture raised")
                    cands = []
            else:
                from ..ops.region import RegionCapture

                src = plan.input
                predicate = None
                ops = ("agg",)
                if isinstance(src, lp.Filter):
                    predicate = src.predicate
                    src = src.input
                    ops = ("filter", "agg")
                cands = [RegionCapture(src, predicate, plan.groupby,
                                       plan.aggregations, ops)]
            for cand in cands:
                if plan.groupby:
                    from ..ops.grouped_stage import try_build_grouped_agg_stage

                    if try_build_grouped_agg_stage(
                        cand.source.schema, cand.predicate, cand.groupby,
                        cand.aggregations
                    ) is not None:
                        return DeviceGroupedAgg(
                            translate(cand.source, config), cand.predicate,
                            cand.groupby, cand.aggregations, plan.schema,
                            region_ops=cand.ops)
                else:
                    from ..ops.stage import try_build_filter_agg_stage

                    if try_build_filter_agg_stage(
                        cand.source.schema, cand.predicate, cand.aggregations
                    ) is not None:
                        return DeviceFilterAgg(
                            translate(cand.source, config), cand.predicate,
                            cand.aggregations, plan.schema,
                            region_ops=cand.ops)
        child = translate(plan.input, config)
        if plan.groupby:
            return HashAggregate(child, plan.groupby, plan.aggregations, plan.schema)
        return UngroupedAggregate(child, plan.aggregations, plan.schema)

    if isinstance(plan, lp.MapGroups):
        return PhysMapGroups(translate(plan.input, config), plan.groupby,
                             plan.udf_expr, plan.schema)

    if isinstance(plan, lp.Distinct):
        return Dedup(translate(plan.input, config), plan.on, plan.schema)

    if isinstance(plan, lp.Pivot):
        return PhysPivot(translate(plan.input, config), plan.groupby, plan.pivot_col,
                         plan.value_col, plan.agg_op, plan.names, plan.schema)

    if isinstance(plan, lp.Window):
        return PhysWindow(translate(plan.input, config), plan.window_exprs, plan.spec, plan.schema)

    if isinstance(plan, lp.Concat):
        return PhysConcat([translate(c, config) for c in plan.inputs], plan.schema)

    if isinstance(plan, lp.Join):
        merged_keys, right_rename = plan.output_naming()
        if plan.how == "cross":
            return CrossJoin(translate(plan.left, config),
                             translate(plan.right, config), right_rename, plan.schema)
        # Cost-based build-side selection (reference: translate_join.rs strategy
        # pick + broadcast_join_size_bytes): the right side is the hash build;
        # when the LEFT side is estimated much smaller (and small enough to
        # hold), swap sides so the small side builds, restoring the original
        # column order with a Project.
        if plan.how == "inner" and plan.strategy is None and not right_rename:
            from ..expressions import col as _col
            from .stats import estimate_bytes

            lb = estimate_bytes(plan.left)
            rb = estimate_bytes(plan.right)
            # build on the smaller side unconditionally (no absolute size cap:
            # the build side is fully materialized either way, so picking the
            # smaller one strictly reduces memory AND build time; the 2x
            # hysteresis avoids churn on near-equal estimates)
            if lb is not None and rb is not None and lb < rb / 2:
                swapped = lp.Join(plan.right, plan.left, plan.right_on, plan.left_on,
                                  "inner")
                s_merged, s_rename = swapped.output_naming()
                if not s_rename and (set(swapped.schema.column_names())
                                     == set(plan.schema.column_names())):
                    hj = HashJoin(translate(plan.right, config),
                                  translate(plan.left, config),
                                  plan.right_on, plan.left_on, "inner",
                                  s_merged, s_rename, swapped.schema,
                                  plan.null_equals_null)
                    return Project(hj, [_col(f.name) for f in plan.schema], plan.schema)
        return HashJoin(translate(plan.left, config), translate(plan.right, config),
                        plan.left_on, plan.right_on, plan.how,
                        merged_keys, right_rename, plan.schema, plan.null_equals_null,
                        plan.strategy)

    if isinstance(plan, lp.Repartition):
        return PhysRepartition(translate(plan.input, config), plan.num_partitions,
                               plan.scheme, plan.by, plan.schema)

    if isinstance(plan, lp.IntoPartitions):
        return PhysRepartition(translate(plan.input, config), plan.num_partitions,
                               "into", None, plan.schema)

    if isinstance(plan, lp.IntoBatches):
        return PhysIntoBatches(translate(plan.input, config), plan.batch_size, plan.schema)

    if isinstance(plan, lp.Sink):
        return PhysWrite(translate(plan.input, config), plan.info, plan.schema)

    raise NotImplementedError(f"cannot translate {type(plan).__name__}")
