"""Window specification.

Reference parity: daft/window.py:12 (Window: partition_by/order_by/rows_between/
range_between) and src/daft-dsl/src/expr/window.rs:92 (WindowSpec).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union


class Window:
    """Immutable window spec built fluently:
    Window().partition_by("k").order_by("t").rows_between(Window.unbounded_preceding, 0)
    """

    unbounded_preceding = object()
    unbounded_following = object()
    current_row = 0

    def __init__(self):
        self.partition_by_exprs: List = []
        self.order_by_exprs: List = []
        self.descending: List[bool] = []
        self.nulls_first: List[bool] = []
        # frame: None = default (whole partition, or running if ordered)
        self.frame_type: Optional[str] = None  # 'rows' | 'range'
        self.frame_start = None
        self.frame_end = None
        self.min_periods: int = 1

    def _copy(self) -> "Window":
        w = Window.__new__(Window)
        w.partition_by_exprs = list(self.partition_by_exprs)
        w.order_by_exprs = list(self.order_by_exprs)
        w.descending = list(self.descending)
        w.nulls_first = list(self.nulls_first)
        w.frame_type = self.frame_type
        w.frame_start = self.frame_start
        w.frame_end = self.frame_end
        w.min_periods = self.min_periods
        return w

    def partition_by(self, *cols) -> "Window":
        from .plan.builder import _to_exprs

        w = self._copy()
        w.partition_by_exprs.extend(_to_exprs(cols))
        return w

    def order_by(self, *cols, desc: Union[bool, Sequence[bool]] = False,
                 nulls_first: Optional[Union[bool, Sequence[bool]]] = None) -> "Window":
        from .plan.builder import _to_exprs

        w = self._copy()
        exprs = _to_exprs(cols)
        descs = [desc] * len(exprs) if isinstance(desc, bool) else list(desc)
        if nulls_first is None:
            nfs = [d for d in descs]
        elif isinstance(nulls_first, bool):
            nfs = [nulls_first] * len(exprs)
        else:
            nfs = list(nulls_first)
        w.order_by_exprs.extend(exprs)
        w.descending.extend(descs)
        w.nulls_first.extend(nfs)
        return w

    def rows_between(self, start, end, min_periods: int = 1) -> "Window":
        w = self._copy()
        w.frame_type = "rows"
        w.frame_start = start
        w.frame_end = end
        w.min_periods = min_periods
        return w

    def range_between(self, start, end, min_periods: int = 1) -> "Window":
        w = self._copy()
        w.frame_type = "range"
        w.frame_start = start
        w.frame_end = end
        w.min_periods = min_periods
        return w

    def __repr__(self) -> str:
        # must capture EVERY semantic field: the optimizer merges specs by repr()
        parts = []
        if self.partition_by_exprs:
            parts.append(f"partition_by={[repr(e) for e in self.partition_by_exprs]}")
        if self.order_by_exprs:
            parts.append(
                f"order_by={[repr(e) for e in self.order_by_exprs]}"
                f" desc={self.descending} nulls_first={self.nulls_first}"
            )
        if self.frame_type:
            def b(x):
                if x is Window.unbounded_preceding:
                    return "unbounded_preceding"
                if x is Window.unbounded_following:
                    return "unbounded_following"
                return str(x)

            parts.append(f"{self.frame_type}=[{b(self.frame_start)},{b(self.frame_end)}]"
                         f" min_periods={self.min_periods}")
        return "Window(" + ", ".join(parts) + ")"
