"""``python -m daft_tpu.gateway`` — run the gateway as a standalone server.

    python -m daft_tpu.gateway --port 8642 --demo-rows 200000

Prints ``gateway listening on HOST:PORT`` once the socket is bound (tests
and scripts parse this line to learn the chosen port when --port 0), then
serves until SIGINT/SIGTERM. ``--demo-rows N`` registers a deterministic
demo table ``t`` (the BENCH_SERVE shape: k = i%601, v = float(i%8191),
w = i%97) — deterministic ON PURPOSE: the same rows on every launch means
the same source content fingerprints, which is what lets a relaunched
gateway resume its predecessor's committed checkpoints and hit its persisted
result keys (the restartable-driver demo and the kill -9 test both ride
this). Real deployments register tables in-process via
``GatewayServer.set_table`` instead.
"""

from __future__ import annotations

import argparse
import signal
import threading


def _demo_table(rows: int):
    import daft_tpu as dt

    return dt.from_pydict({
        "k": [i % 601 for i in range(rows)],
        "v": [float(i % 8191) for i in range(rows)],
        "w": [i % 97 for i in range(rows)],
    })


def main(argv=None) -> int:
    from .server import GatewayServer

    p = argparse.ArgumentParser(
        prog="python -m daft_tpu.gateway",
        description="daft_tpu serving gateway (wire protocol over TCP)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="bind port (0 = pick a free port; printed on stdout)")
    p.add_argument("--demo-rows", type=int, default=0, metavar="N",
                   help="register a deterministic N-row demo table 't'")
    p.add_argument("--max-concurrent", type=int, default=None,
                   help="serving worker threads (default: ExecutionConfig)")
    args = p.parse_args(argv)

    tables = {"t": _demo_table(args.demo_rows)} if args.demo_rows > 0 else None
    server = GatewayServer(host=args.host, port=args.port, tables=tables,
                           max_concurrent=args.max_concurrent)
    server.start()
    print(f"gateway listening on {server.host}:{server.port}", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
