"""Gateway result cache: encoded result payloads keyed by content fingerprint.

The cache key is ``checkpoint.stages.query_fingerprint`` — a digest over the
physical plan's structural walk **plus the content fingerprint of every
in-memory source column**. Source data changing therefore changes the key,
so invalidation is free and exact: a stale entry can never be served because
a mutated source simply hashes to a different key (the stale bytes age out
of the LRU instead). Queries that cannot be keyed (fingerprint ``None``)
bypass the cache entirely.

Entries hold the *wire-encoded* chunks (compressed Arrow IPC streams), not
MicroPartitions: a hit streams straight to the socket with zero re-encoding,
and the byte budget meters exactly what the cache actually holds.

Budget and accounting: ``DAFT_TPU_GATEWAY_RESULT_CACHE`` bounds resident
bytes (0 disables the cache); evictions are LRU. When the host memory ledger
is active (DAFT_TPU_MEMORY_LIMIT > 0) cached bytes are tracked against
it so serving pressure and execution pressure share one accounting.
Counters: ``result_cache_hits`` / ``result_cache_misses`` /
``result_cache_evictions`` and the ``result_cache_bytes`` gauge.

Thrash detection (flight-recorder hook): a sliding window of recent lookups;
when the window shows repeat traffic (fewer distinct keys than lookups) yet
the hit rate sits below ``DAFT_TPU_GATEWAY_THRASH_RATIO``, the cache is
churning — the budget is too small for the working set — and ``note_thrash``
returns a detail string the gateway turns into a ``cache_thrash`` anomaly
trigger so ``make doctor`` can diagnose it from the dump alone.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from ..observability.metrics import registry
from ..utils.env import env_float, env_int


def result_cache_budget() -> int:
    """DAFT_TPU_GATEWAY_RESULT_CACHE: resident-byte budget for cached result
    payloads (0 disables result caching)."""
    return env_int("DAFT_TPU_GATEWAY_RESULT_CACHE", 64 * 1024 * 1024, lo=0)


class CachedResult:
    """One cached query result: wire-ready chunks + the fetch-reply footer
    fields (rows/columns) so a hit never touches the engine."""

    __slots__ = ("chunks", "rows", "columns", "nbytes")

    def __init__(self, chunks: List[bytes], rows: int, columns: List[str]):
        self.chunks = chunks
        self.rows = rows
        self.columns = columns
        self.nbytes = sum(len(c) for c in chunks)


class ResultCache:
    """LRU over encoded result payloads, bounded by a byte budget, shared
    across tenants (the fingerprint key embeds the data identity, so a
    cross-tenant hit is by construction the same bytes)."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self._budget = (result_cache_budget() if budget_bytes is None
                        else budget_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, CachedResult]" = OrderedDict()
        self._bytes = 0
        self._ledgered = 0  # bytes registered with the host memory ledger
        # thrash window: (key, hit) per lookup, newest last
        self._window: deque = deque(
            maxlen=env_int("DAFT_TPU_GATEWAY_THRASH_WINDOW", 32, lo=4))
        self._thrash_ratio = min(
            env_float("DAFT_TPU_GATEWAY_THRASH_RATIO", 0.25, lo=0.0), 1.0)

    @property
    def budget_bytes(self) -> int:
        return self._budget

    def _ledger_sync(self, manager) -> None:
        """Mirror resident bytes into the host ledger (advisory: only when a
        limit is configured; a 0-limit ledger is untracked by contract)."""
        if manager.limit_bytes() <= 0:
            return
        if self._bytes > self._ledgered:
            manager.track(self._bytes - self._ledgered)
        elif self._ledgered > self._bytes:
            manager.release(self._ledgered - self._bytes)
        self._ledgered = self._bytes

    def get(self, key: Optional[str]) -> Optional[CachedResult]:
        """Lookup; bumps LRU recency and the hit/miss counters. ``None`` key
        (unkeyable query) is a silent bypass, not a miss."""
        if key is None or self._budget <= 0:
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
            self._window.append((key, entry is not None))
        if entry is None:
            registry().inc("result_cache_misses")
        else:
            registry().inc("result_cache_hits")
        return entry

    def put(self, key: Optional[str], entry: CachedResult) -> bool:
        """Insert (idempotent; re-insert refreshes recency). Entries larger
        than the whole budget are refused rather than evicting everything."""
        if key is None or self._budget <= 0 or entry.nbytes > self._budget:
            return False
        from ..memory.manager import manager

        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = entry
            self._bytes += entry.nbytes
            while self._bytes > self._budget and len(self._entries) > 1:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                evicted += 1
            self._ledger_sync(manager())
            resident = self._bytes
        if evicted:
            registry().inc("result_cache_evictions", evicted)
        registry().set_gauge("result_cache_bytes", resident)
        return True

    def note_thrash(self) -> Optional[str]:
        """Inspect the lookup window; returns an anomaly detail string when
        repeat traffic is missing the cache (budget below working set), else
        None. Consumes the window on detection so one sustained thrash burst
        yields one trigger, not one per lookup."""
        with self._lock:
            if len(self._window) < self._window.maxlen:
                return None
            lookups = list(self._window)
            distinct = len({k for k, _ in lookups})
            hits = sum(1 for _, h in lookups if h)
            rate = hits / len(lookups)
            if distinct >= len(lookups) or rate >= self._thrash_ratio:
                return None
            self._window.clear()
            return (f"result-cache thrash: hit rate {rate:.2f} over last "
                    f"{len(lookups)} lookups ({distinct} distinct keys) — "
                    f"budget {self._budget} bytes below the repeat working "
                    f"set; raise DAFT_TPU_GATEWAY_RESULT_CACHE")

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "budget": self._budget}

    def clear(self) -> None:
        from ..memory.manager import manager

        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._ledger_sync(manager())
        registry().set_gauge("result_cache_bytes", 0)
