"""Gateway: the network front door — wire-protocol serving over TCP.

The serving tier (PR 8) made the warm engine concurrent and multi-tenant
in-process; the gateway puts it on the network without re-deriving any of
it: a length-framed socket protocol (protocol.py) fronts one
ServingSession, so tenant fairness, QoS weights and queue caps, HBM
admission, prepared-plan reuse, and cooperative cancellation all apply
unchanged to remote clients.

    server:  python -m daft_tpu.gateway --port 8642 --demo-rows 200000
    client:  from daft_tpu.gateway import GatewayClient
             with GatewayClient(host, port, tenant="acme", token=t) as c:
                 print(c.query("SELECT COUNT(*) AS n FROM t"))

What the network layer adds on top of the session (see server.py):

- per-tenant shared-secret auth (``DAFT_TPU_GATEWAY_TOKENS``),
- server-scoped prepared handles that survive reconnects,
- a fingerprint-keyed result cache (``DAFT_TPU_GATEWAY_RESULT_CACHE``)
  with exact source-change invalidation,
- a restartable driver: results checkpoint through the PR 9
  StageCheckpointer, so a killed-and-relaunched gateway resumes committed
  work from disk instead of recomputing (and never serves a stale result —
  the checkpoint key embeds the source content fingerprints).

Observability: gateway_*/result_cache_* counters on /metrics, a
GatewayQueryRecord per query (event log schema v11), the /api/gateway
dashboard route, and flight-recorder ``gateway_error`` / ``cache_thrash``
anomaly triggers that ``make doctor`` triages from dumps alone.
"""

from .client import GatewayClient
from .protocol import GatewayError, WireError, parse_token_map
from .result_cache import CachedResult, ResultCache
from .server import GatewayServer

__all__ = [
    "CachedResult",
    "GatewayClient",
    "GatewayError",
    "GatewayServer",
    "ResultCache",
    "WireError",
    "parse_token_map",
]
