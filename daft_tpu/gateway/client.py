"""GatewayClient: the Python client for the gateway wire protocol.

Blocking, one socket per client, prepared-statement shaped::

    from daft_tpu.gateway import GatewayClient

    with GatewayClient("127.0.0.1", 8642, tenant="acme", token="s3cr3t") as c:
        h = c.prepare("SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k")
        qid = c.execute(handle=h)
        for batch in c.fetch(qid):          # pyarrow RecordBatches, streamed
            ...
        out = c.query("SELECT COUNT(*) AS n FROM t")   # one-shot -> pydict

Reconnect semantics: handles are SERVER-scoped, so a client that redials
keeps executing by handle. ``execute`` additionally remembers the SQL text
behind each handle it prepared, and transparently re-prepares on an
``unknown_handle`` reply (the handle aged out of the server's bounded map) —
the caller never sees the round trip. Typed failures raise
:class:`GatewayError` with ``.code`` from the protocol vocabulary
(``bad_token``, ``over_capacity``, ``cancelled``, ...).
"""

from __future__ import annotations

import socket
from typing import Dict, Iterator, List, Optional

from . import protocol as proto
from .protocol import GatewayError


class GatewayClient:
    """Blocking gateway connection for one tenant (see module doc)."""

    def __init__(self, host: str, port: int, tenant: str = "default",
                 token: str = "", timeout: Optional[float] = None,
                 connect_retries: int = 0):
        self.host = host
        self.port = port
        self.tenant = tenant
        self.token = token
        self.timeout = timeout
        self._connect_retries = connect_retries
        self._sock: Optional[socket.socket] = None
        # handle -> SQL text, for transparent re-prepare after server-side
        # handle eviction or a gateway restart
        self._prepared_sql: Dict[str, str] = {}
        # terminal fetch frame ({rows, columns, source, chunks}) and the
        # last execute's source tier, for caller-side attribution
        self.last_fetch: dict = {}
        self.last_source = ""
        self._connect()

    # ---- connection ----------------------------------------------------------------
    def _connect(self) -> None:
        import time as _time

        attempt = 0
        while True:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout)
                break
            except OSError:
                attempt += 1
                if attempt > self._connect_retries:
                    raise
                _time.sleep(min(0.05 * (2 ** attempt), 1.0))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        proto.send_json(self._sock, {"verb": "hello", "tenant": self.tenant,
                                     "token": self.token})
        self._reply()

    def reconnect(self) -> None:
        """Redial and re-authenticate (prepared handles survive server-side;
        this client's handle->SQL memory survives client-side)."""
        self.close()
        self._connect()

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            proto.send_json(self._sock, {"verb": "bye"})
            proto.recv_json(self._sock)
        except (OSError, GatewayError, EOFError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = None

    def __enter__(self) -> "GatewayClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _reply(self) -> dict:
        obj = proto.recv_json(self._sock)
        if not obj.get("ok", False):
            raise GatewayError(obj.get("code", "error"),
                               obj.get("error", "gateway error"))
        return obj

    def _request(self, obj: dict) -> dict:
        if self._sock is None:
            raise GatewayError("bad_request", "client is closed")
        proto.send_json(self._sock, obj)
        return self._reply()

    # ---- verbs ---------------------------------------------------------------------
    def prepare(self, sql: str) -> str:
        """Plan `sql` server-side; returns a handle that survives reconnects
        (and, via client-side re-prepare, server restarts)."""
        reply = self._request({"verb": "prepare", "sql": sql})
        handle = reply["handle"]
        self._prepared_sql[handle] = sql
        return handle

    def execute(self, sql: Optional[str] = None,
                handle: Optional[str] = None) -> str:
        """Admit one query (by SQL text or prepared handle); returns its
        query id immediately — results stream on :meth:`fetch`."""
        if (sql is None) == (handle is None):
            raise GatewayError("bad_request",
                               "execute takes exactly one of sql / handle")
        req = ({"verb": "execute", "sql": sql} if sql is not None
               else {"verb": "execute", "handle": handle})
        try:
            reply = self._request(req)
        except GatewayError as e:
            known = handle is not None and handle in self._prepared_sql
            if e.code != "unknown_handle" or not known:
                raise
            # the server aged the handle out (bounded map / restart):
            # re-prepare from the remembered SQL and retry once
            fresh = self.prepare(self._prepared_sql[handle])
            reply = self._request({"verb": "execute", "handle": fresh})
        self.last_source = reply.get("source", "")
        return reply["query_id"]

    def fetch(self, query_id: str,
              timeout: Optional[float] = None) -> Iterator:
        """Stream the query's result as pyarrow RecordBatches. The terminal
        control frame's fields land on ``.last_fetch`` (rows/source/columns)."""
        if self._sock is None:
            raise GatewayError("bad_request", "client is closed")
        req = {"verb": "fetch", "query_id": query_id}
        if timeout is not None:
            req["timeout"] = timeout
        proto.send_json(self._sock, req)
        while True:
            tag, payload = proto.recv_frame(self._sock)
            if tag == proto.TAG_BINARY:
                for batch in proto.decode_result_chunk(payload):
                    yield batch
                continue
            import json as _json

            obj = _json.loads(payload.decode())
            if not obj.get("ok", False):
                raise GatewayError(obj.get("code", "error"),
                                   obj.get("error", "gateway error"))
            self.last_fetch = obj
            return

    def fetch_pydict(self, query_id: str,
                     timeout: Optional[float] = None) -> dict:
        """Fetch and assemble into a column dict (empty result keeps the
        schema via the terminal frame's column list)."""
        out: dict = {}
        cols: List[str] = []
        for batch in self.fetch(query_id, timeout=timeout):
            d = batch.to_pydict()
            cols = cols or list(d)
            for k, v in d.items():
                out.setdefault(k, []).extend(v)
        for name in self.last_fetch.get("columns", []):
            out.setdefault(name, [])
        return out

    def query(self, sql: str, timeout: Optional[float] = None) -> dict:
        """One-shot convenience: execute + fetch_pydict."""
        return self.fetch_pydict(self.execute(sql=sql), timeout=timeout)

    def cancel(self, query_id: str) -> bool:
        """Cancel a submitted query; True when the cancellation was
        delivered (the fetch will then answer a typed ``cancelled`` error)."""
        return bool(self._request({"verb": "cancel",
                                   "query_id": query_id}).get("cancelled"))

    def stats(self) -> dict:
        """Server-side gateway/serving metrics + result-cache occupancy."""
        return self._request({"verb": "stats"})
