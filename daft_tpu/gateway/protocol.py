"""Gateway wire protocol: length-framed TCP in the fetch_server style.

Reference parity: the reference's Arrow Flight serving surface (flight
server ``do_get`` streaming) mapped onto the same framing discipline the
shuffle transport already speaks (distributed/fetch_server.py) — but over a
raw socket with explicit length prefixes instead of pickle frames, because
gateway clients are untrusted: nothing on this wire is ever unpickled.

Frame layout (everything big-endian)::

    +----------------+-----+----------------------+
    | length: u32    | tag | payload (length - 1) |
    +----------------+-----+----------------------+

``tag`` is one byte: ``J`` — a UTF-8 JSON control object (requests, replies,
typed errors); ``B`` — a binary payload chunk (one self-contained compressed
Arrow IPC stream holding one result batch). A fetch reply is zero or more
``B`` frames followed by one terminal ``J`` frame; every other exchange is
one ``J`` request -> one ``J`` reply.

Verbs (client -> server, all ``J``)::

    {"verb": "hello", "tenant": t, "token": s}   auth; must be first
    {"verb": "prepare", "sql": q}                -> {"ok", "handle", ...}
    {"verb": "execute", "sql"|"handle": ...}     -> {"ok", "query_id", ...}
    {"verb": "fetch", "query_id": id}            -> B* then {"ok", "done", ...}
    {"verb": "cancel", "query_id": id}           -> {"ok", "cancelled"}
    {"verb": "stats"}                            -> {"ok", "metrics", ...}
    {"verb": "bye"}                              closes the connection

Error replies are ``{"ok": false, "code": c, "error": msg}`` with a stable
code vocabulary (``bad_token``, ``bad_frame``, ``frame_too_large``,
``unknown_handle``, ``unknown_query``, ``over_capacity``, ``cancelled``,
``exec_error``, ``bad_request``, ``unknown_verb``) so clients branch on the
code, never on message text.

Defensive bounds: frames larger than ``DAFT_TPU_GATEWAY_MAX_FRAME`` are
refused with a typed error before any allocation (a bogus length prefix can
never balloon server memory), and a connection that dies mid-frame raises a
clean :class:`WireError` instead of feeding a torn payload downstream.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, Iterator, List, Optional, Tuple

from ..utils.env import env_int, env_str

TAG_JSON = b"J"
TAG_BINARY = b"B"

_LEN = struct.Struct(">I")


def max_frame_bytes() -> int:
    """DAFT_TPU_GATEWAY_MAX_FRAME: largest frame either side accepts (bytes);
    floor 64 KiB so a control frame always fits."""
    return env_int("DAFT_TPU_GATEWAY_MAX_FRAME", 64 * 1024 * 1024,
                   lo=64 * 1024)


class WireError(Exception):
    """A typed wire-protocol failure. ``code`` is from the stable error
    vocabulary above; raised client-side for error replies and server-side
    for malformed traffic (the server answers it as a typed error frame)."""

    def __init__(self, code: str, message: str):
        self.code = code
        super().__init__(message)


# GatewayError is the client-facing name for the same condition — one class
# so `except GatewayError as e: e.code` works symmetrically on either side.
GatewayError = WireError


def parse_token_map(raw: Optional[str] = None) -> Dict[str, str]:
    """DAFT_TPU_GATEWAY_TOKENS -> {tenant: token}. Format:
    ``tenant:token,tenant2:token2``. An empty/unset map selects OPEN mode
    (any tenant accepted — development and tests only; production deployments
    set the map). Malformed entries are skipped, not fatal: a typo'd entry
    locks out one tenant, never the whole gateway."""
    raw = env_str("DAFT_TPU_GATEWAY_TOKENS", "") if raw is None else raw
    out: Dict[str, str] = {}
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry or ":" not in entry:
            continue
        tenant, token = entry.split(":", 1)
        if tenant:
            out[tenant] = token
    return out


# ---- framing ------------------------------------------------------------------------

def send_frame(sock, tag: bytes, payload: bytes) -> None:
    """One frame on the wire. sendall provides the stream's backpressure: a
    client that stops reading stalls the server's send buffer, which stalls
    the fetch loop — no unbounded server-side buffering."""
    sock.sendall(_LEN.pack(len(payload) + 1) + tag)
    if payload:
        sock.sendall(payload)


def send_json(sock, obj: dict) -> None:
    send_frame(sock, TAG_JSON, json.dumps(obj).encode())


def send_error(sock, code: str, message: str) -> None:
    send_json(sock, {"ok": False, "code": code, "error": message})


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise WireError(
                "bad_frame",
                f"connection closed mid-frame ({len(buf)} of {n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock, max_frame: Optional[int] = None) -> Tuple[bytes, bytes]:
    """Read one frame -> (tag, payload). Raises EOFError on a clean
    between-frames close (the peer said everything it had to say) and
    :class:`WireError` on truncation or an oversized/underssized length
    prefix — torn frames never propagate as data."""
    head = b""
    try:
        head = sock.recv(_LEN.size)
    except OSError as e:
        raise WireError("bad_frame", f"socket error reading frame: {e}")
    if not head:
        raise EOFError("connection closed")
    if len(head) < _LEN.size:
        head += _recv_exact(sock, _LEN.size - len(head))
    (length,) = _LEN.unpack(head)
    cap = max_frame_bytes() if max_frame is None else max_frame
    if length > cap:
        raise WireError("frame_too_large",
                        f"frame of {length} bytes exceeds the "
                        f"{cap}-byte cap (DAFT_TPU_GATEWAY_MAX_FRAME)")
    if length < 1:
        raise WireError("bad_frame", "zero-length frame (missing tag byte)")
    body = _recv_exact(sock, length)
    return body[:1], body[1:]


def recv_json(sock, max_frame: Optional[int] = None) -> dict:
    tag, payload = recv_frame(sock, max_frame)
    if tag != TAG_JSON:
        raise WireError("bad_frame",
                        f"expected a JSON control frame, got tag {tag!r}")
    try:
        obj = json.loads(payload.decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise WireError("bad_frame", f"undecodable control frame: {e}")
    if not isinstance(obj, dict):
        raise WireError("bad_frame", "control frame must be a JSON object")
    return obj


# ---- Arrow IPC payload codec --------------------------------------------------------

def encode_result_chunks(parts: List) -> List[bytes]:
    """MicroPartitions -> wire chunks: one self-contained compressed Arrow
    IPC stream per non-empty batch (the same wire format the shuffle
    transport and the checkpoint store write — ExecutionConfig's
    shuffle_compression codec travels in the IPC message headers, so the
    client needs no codec negotiation). Per-batch framing bounds every frame
    by the engine's morsel size and lets the client decode chunk k while
    chunk k+1 is still on the wire."""
    import io

    import pyarrow.ipc as ipc

    from ..config import execution_config

    compression = execution_config().shuffle_compression
    opts = ipc.IpcWriteOptions(
        compression=None if compression == "none" else compression)
    chunks: List[bytes] = []
    for part in parts:
        for b in part.batches:
            if b.num_rows == 0:
                continue
            t = b.to_arrow()
            sink = io.BytesIO()
            with ipc.new_stream(sink, t.schema, options=opts) as w:
                w.write_table(t)
            chunks.append(sink.getvalue())
    return chunks


def decode_result_chunk(payload: bytes) -> Iterator:
    """One wire chunk -> pyarrow RecordBatches (decompression handled by the
    IPC reader; the codec rides the message headers)."""
    import io

    import pyarrow.ipc as ipc

    with ipc.open_stream(io.BytesIO(payload)) as r:
        for batch in r:
            yield batch
