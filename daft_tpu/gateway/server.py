"""GatewayServer: the network front door over a ServingSession.

One process-wide serving surface: a listener thread accepts TCP connections,
one daemon thread per connection speaks the length-framed protocol
(protocol.py), and every query funnels through a single
:class:`~daft_tpu.serving.ServingSession` — so the gateway inherits the
serving tier's whole QoS stack unchanged: per-tenant weighted round-robin
admission with depth caps (typed ``over_capacity`` wire error), the HBM
admission controller, the prepared-query cache, and cooperative
cancellation (the ``cancel`` verb trips the same token the engine's
checkpoints poll — a cancel on the wire lands between streamed partitions,
not at the next query boundary).

Three result tiers, cheapest first, consulted at ``execute``:

1. **Result cache** (result_cache.py) — wire-encoded chunks keyed by
   ``query_fingerprint`` (plan structure + source content fingerprints);
   a hit streams without touching the engine.
2. **Checkpoint restore** — with ``DAFT_TPU_CHECKPOINT_DIR`` set, a
   committed result under ``{root}/{fingerprint}/result`` is loaded via the
   PR 9 StageCheckpointer. This IS the restartable driver: the checkpoint
   tree is the persisted {plan fingerprint -> result} map, so a gateway
   killed mid-replay and relaunched serves committed work from disk and
   re-runs only what never committed — never a client-visible wrong result
   (the fingerprint embeds the source data identity).
3. **Execute** — submit to the ServingSession; on success the result is
   committed to the checkpointer and inserted into the result cache.

Prepared handles are server-scoped, not connection-scoped: a handle is the
stable hash of the plan's (skeleton, literals) structure, kept in a bounded
map that survives reconnects — a client that drops and redials resumes
executing by handle with no re-prepare round trip.

Auth: shared-secret per tenant (``DAFT_TPU_GATEWAY_TOKENS``); an empty map
is OPEN mode for development. Failures answer ``bad_token``, count
``gateway_auth_failures``, and fire a flight-recorder ``gateway_error``
anomaly so repeated bad tokens surface in ``make doctor`` triage.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional

from ..observability import GatewayQueryRecord, notify, subscribers_active
from ..observability.metrics import registry
from ..serving import ServingSession, TenantQueueFull, plan_structure
from ..utils.env import env_int
from . import protocol as proto
from .result_cache import CachedResult, ResultCache


def handle_cap() -> int:
    """DAFT_TPU_GATEWAY_HANDLES: prepared handles the server retains across
    all tenants/connections (LRU past the cap; a client holding an evicted
    handle gets unknown_handle and re-prepares)."""
    return env_int("DAFT_TPU_GATEWAY_HANDLES", 256, lo=8)


def _handle_of(builder) -> str:
    """Prepared-statement handle: stable digest of the plan's (skeleton,
    literals). Deterministic across connections AND server restarts for the
    same query text over the same registered tables, which is what lets a
    reconnecting client resume by handle."""
    skel, lits = plan_structure(builder.plan)
    return hashlib.blake2s(repr((skel, lits)).encode(),
                           digest_size=12).hexdigest()


class _QueryState:
    """Per-execute bookkeeping between the execute and fetch verbs."""

    __slots__ = ("tenant", "future", "cached", "source", "fingerprint",
                 "schema", "ckpt", "handle", "t0")

    def __init__(self, tenant: str, source: str, future=None, cached=None,
                 fingerprint=None, schema=None, ckpt=None, handle: str = ""):
        self.tenant = tenant
        self.source = source        # executed | result_cache | checkpoint
        self.future = future
        self.cached = cached        # CachedResult when already materialized
        self.fingerprint = fingerprint
        self.schema = schema
        self.ckpt = ckpt
        self.handle = handle
        self.t0 = time.perf_counter()


class GatewayServer:
    """Socket front door over one ServingSession (see module doc).

    Args:
        host/port: bind address (port 0 picks a free port; read ``.port``).
        tokens: {tenant: token} override; None reads DAFT_TPU_GATEWAY_TOKENS.
        tables: {name: DataFrame} initial SQL bindings (``set_table`` later).
        max_concurrent: ServingSession worker threads.
        result_cache_budget: byte budget override for the result cache.
    """

    # in-flight execute->fetch states retained; far above any sane number of
    # unfetched queries per process, it only bounds a client that executes
    # forever without fetching
    _QUERY_STATE_CAP = 4096

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 tokens: Optional[Dict[str, str]] = None, tables=None,
                 max_concurrent: Optional[int] = None,
                 result_cache_budget: Optional[int] = None):
        self._tokens = (proto.parse_token_map() if tokens is None
                        else dict(tokens))
        self._session = ServingSession(max_concurrent=max_concurrent)
        self.cache = ResultCache(result_cache_budget)
        self._lock = threading.Lock()
        self._tables: Dict[str, object] = dict(tables or {})
        self._handles: "OrderedDict[str, object]" = OrderedDict()
        self._queries: "OrderedDict[str, _QueryState]" = OrderedDict()
        self._closed = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self._listener.settimeout(0.2)
        self.host, self.port = self._listener.getsockname()[:2]
        self._accept_thread: Optional[threading.Thread] = None

    # ---- lifecycle -----------------------------------------------------------------
    def start(self) -> "GatewayServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="daft-gateway-accept")
        self._accept_thread.start()
        return self

    def close(self) -> None:
        self._closed.set()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        try:
            self._listener.close()
        except OSError:
            pass
        self._session.close()

    def __enter__(self) -> "GatewayServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def set_table(self, name: str, df) -> None:
        """(Re)bind a SQL table name. Rebinding flows straight into result
        correctness: new source data -> new content fingerprints -> new cache
        keys, so stale cached results are unreachable by construction."""
        with self._lock:
            self._tables[name] = df

    # ---- accept loop (fetch_server idiom: backoff on error, never die) -------------
    def _accept_loop(self) -> None:
        backoff = 0.005
        while not self._closed.is_set():
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                if self._closed.is_set():
                    return
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.25)
                continue
            backoff = 0.005
            registry().inc("gateway_connections_total")
            threading.Thread(target=self._serve_connection, args=(conn,),
                             daemon=True, name="daft-gateway-conn").start()

    # ---- per-connection protocol loop ----------------------------------------------
    def _serve_connection(self, conn: socket.socket) -> None:
        reg = registry()
        reg.set_gauge("gateway_active_connections",
                      reg.get("gateway_connections_total")
                      - reg.get("gateway_disconnects_total"))
        tenant = ""
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            tenant = self._handshake(conn)
            if tenant is None:
                return
            while not self._closed.is_set():
                req = proto.recv_json(conn)
                reg.inc("gateway_requests_total")
                verb = req.get("verb", "")
                if verb == "bye":
                    proto.send_json(conn, {"ok": True, "bye": True})
                    return
                handler = getattr(self, f"_verb_{verb}", None)
                if handler is None:
                    proto.send_error(conn, "unknown_verb",
                                     f"unknown verb {verb!r}")
                    reg.inc("gateway_errors_total")
                    continue
                try:
                    handler(conn, tenant, req)
                except proto.WireError as e:
                    # request-level typed failure: answer it, keep serving
                    # this connection (the framing is still intact)
                    proto.send_error(conn, e.code, str(e))
                    reg.inc("gateway_errors_total")
        except EOFError:
            pass  # clean between-frames close
        except proto.WireError as e:
            # framing-level failure (truncated/oversized/undecodable frame):
            # the byte stream can't be resynchronized — answer a typed error
            # so the client sees WHY, then drop the connection
            reg.inc("gateway_errors_total")
            self._flight_error(f"wire error: {e}", tenant)
            try:
                proto.send_error(conn, e.code, str(e))
            except OSError:
                pass
        except OSError as e:
            reg.inc("gateway_errors_total")
            self._flight_error(f"connection error: {e}", tenant)
        finally:
            reg.inc("gateway_disconnects_total")
            reg.set_gauge("gateway_active_connections",
                          max(0.0, reg.get("gateway_connections_total")
                              - reg.get("gateway_disconnects_total")))
            try:
                conn.close()
            except OSError:
                pass

    def _handshake(self, conn) -> Optional[str]:
        """First frame must be hello; returns the authenticated tenant or
        None (error already answered)."""
        req = proto.recv_json(conn)
        if req.get("verb") != "hello":
            proto.send_error(conn, "bad_request",
                             "first frame must be the hello verb")
            registry().inc("gateway_errors_total")
            return None
        tenant = str(req.get("tenant", "") or "")
        token = str(req.get("token", "") or "")
        if not tenant:
            proto.send_error(conn, "bad_request", "hello carries no tenant")
            registry().inc("gateway_errors_total")
            return None
        if self._tokens:
            expected = self._tokens.get(tenant, "")
            if not expected or not hmac.compare_digest(
                    expected.encode(), token.encode()):
                registry().inc("gateway_auth_failures")
                self._flight_error(
                    f"auth failure for tenant {tenant!r}", tenant)
                proto.send_error(conn, "bad_token",
                                 f"bad token for tenant {tenant!r}")
                return None
        proto.send_json(conn, {"ok": True, "server": "daft_tpu-gateway",
                               "open_mode": not self._tokens})
        return tenant

    def _flight_error(self, detail: str, tenant: str = "") -> None:
        from ..observability import flight as _flight

        frec = _flight.recorder()
        if frec is not None:
            frec.trigger("gateway_error", detail=detail, tenant=tenant)

    # ---- query resolution ----------------------------------------------------------
    def _resolve_sql(self, sql_text: str):
        from ..sql import sql as _sql

        with self._lock:
            bindings = dict(self._tables)
        try:
            df = _sql(sql_text, **bindings)
        except Exception as e:  # noqa: BLE001 — client's query text: answer, don't die
            raise proto.WireError("bad_request", f"SQL error: {e}")
        return getattr(df, "_builder", df)

    def _builder_for(self, req: dict):
        """execute/prepare request -> (builder, handle). Registers the handle
        (bounded LRU) so any later connection can execute by handle."""
        handle = req.get("handle")
        if handle is not None:
            with self._lock:
                builder = self._handles.get(handle)
                if builder is not None:
                    self._handles.move_to_end(handle)
            if builder is None:
                raise proto.WireError(
                    "unknown_handle",
                    f"unknown prepared handle {handle!r} (evicted or from "
                    f"another server); re-prepare")
            return builder, handle
        sql_text = req.get("sql")
        if not sql_text:
            raise proto.WireError("bad_request",
                                  "request carries neither sql nor handle")
        builder = self._resolve_sql(str(sql_text))
        handle = _handle_of(builder)
        with self._lock:
            self._handles[handle] = builder
            self._handles.move_to_end(handle)
            while len(self._handles) > handle_cap():
                self._handles.popitem(last=False)
        return builder, handle

    def _fingerprint(self, physical) -> Optional[str]:
        """Content fingerprint (cache/checkpoint key), or None for unkeyable
        plans — those bypass both tiers and always execute."""
        try:
            from ..checkpoint.stages import query_fingerprint

            return query_fingerprint(physical)
        except Exception:  # lint: ignore[broad-except] -- fingerprinting is advisory;
            # an unkeyable plan degrades to always-execute, never to a failure
            return None

    def _checkpointer(self, fingerprint: Optional[str]):
        root = os.environ.get("DAFT_TPU_CHECKPOINT_DIR", "")
        if not root or fingerprint is None:
            return None
        try:
            from ..checkpoint.stages import StageCheckpointer

            return StageCheckpointer(root, f"gw-{fingerprint}")
        except Exception:  # lint: ignore[broad-except] -- checkpointing is advisory;
            # an unusable root degrades to no-restore, never to a failure
            return None

    def _remember(self, qid: str, state: _QueryState) -> None:
        with self._lock:
            self._queries[qid] = state
            while len(self._queries) > self._QUERY_STATE_CAP:
                self._queries.popitem(last=False)

    # ---- verbs ---------------------------------------------------------------------
    def _verb_prepare(self, conn, tenant: str, req: dict) -> None:
        builder, handle = self._builder_for(req)
        entry, hit = self._session.prepared.get_or_plan(builder,
                                                        keep_physical=True)
        proto.send_json(conn, {"ok": True, "handle": handle,
                               "prepared_hit": hit,
                               "columns": entry.physical.schema.column_names()})

    def _verb_execute(self, conn, tenant: str, req: dict) -> None:
        builder, handle = self._builder_for(req)
        entry, _hit = self._session.prepared.get_or_plan(builder,
                                                         keep_physical=True)
        fp = self._fingerprint(entry.physical)
        qid = uuid.uuid4().hex[:12]
        cached = self.cache.get(fp)
        thrash = self.cache.note_thrash()
        if thrash is not None:
            from ..observability import flight as _flight

            frec = _flight.recorder()
            if frec is not None:
                frec.trigger("cache_thrash", detail=thrash, tenant=tenant)
        if cached is not None:
            self._remember(qid, _QueryState(tenant, "result_cache",
                                            cached=cached, fingerprint=fp,
                                            handle=handle))
            proto.send_json(conn, {"ok": True, "query_id": qid,
                                   "source": "result_cache"})
            return
        ckpt = self._checkpointer(fp)
        if ckpt is not None and ckpt.committed("result"):
            parts = ckpt.restore_result("result", entry.physical.schema)
            if parts is not None:
                entry_c = CachedResult(
                    proto.encode_result_chunks(parts),
                    sum(p.num_rows for p in parts),
                    entry.physical.schema.column_names())
                self.cache.put(fp, entry_c)
                self._remember(qid, _QueryState(tenant, "checkpoint",
                                                cached=entry_c,
                                                fingerprint=fp,
                                                handle=handle))
                proto.send_json(conn, {"ok": True, "query_id": qid,
                                       "source": "checkpoint"})
                return
        try:
            fut = self._session.submit(builder, tenant=tenant)
        except TenantQueueFull as e:
            raise proto.WireError("over_capacity", str(e))
        self._remember(fut.query_id, _QueryState(
            tenant, "executed", future=fut, fingerprint=fp,
            schema=entry.physical.schema, ckpt=ckpt, handle=handle))
        proto.send_json(conn, {"ok": True, "query_id": fut.query_id,
                               "source": "executed"})

    def _state_for(self, tenant: str, req: dict) -> (str, _QueryState):
        qid = str(req.get("query_id", "") or "")
        with self._lock:
            state = self._queries.get(qid)
        # tenant check folds into unknown_query: another tenant's query ids
        # are indistinguishable from nonexistent ones (no probing oracle)
        if state is None or state.tenant != tenant:
            raise proto.WireError("unknown_query",
                                  f"unknown query id {qid!r}")
        return qid, state

    def _verb_fetch(self, conn, tenant: str, req: dict) -> None:
        from ..cancellation import QueryCancelled

        qid, state = self._state_for(tenant, req)
        entry_c = state.cached
        error: Optional[str] = None
        if entry_c is None:
            try:
                parts = state.future.result(
                    timeout=req.get("timeout"))
                entry_c = CachedResult(
                    proto.encode_result_chunks(parts),
                    sum(p.num_rows for p in parts),
                    state.schema.column_names())
                # publish AFTER success, durable first: a kill between commit
                # and cache-insert just means the relaunch restores from disk
                if state.ckpt is not None:
                    state.ckpt.commit_result("result", parts)
                self.cache.put(state.fingerprint, entry_c)
            except QueryCancelled as e:
                self._finish(qid, state, 0, error=f"cancelled: {e}")
                raise proto.WireError("cancelled", str(e))
            except TimeoutError as e:
                # not terminal: the query is still running; the client may
                # fetch again (state stays registered)
                raise proto.WireError("timeout", str(e))
            except Exception as e:  # noqa: BLE001 — execution error crosses the wire typed
                error = f"{type(e).__name__}: {e}"
                self._flight_error(f"query {qid} failed: {error}", tenant)
                self._finish(qid, state, 0, error=error)
                raise proto.WireError("exec_error", error)
        streamed = 0
        for chunk in entry_c.chunks:
            proto.send_frame(conn, proto.TAG_BINARY, chunk)
            streamed += len(chunk)
        registry().inc("gateway_bytes_streamed", streamed)
        proto.send_json(conn, {"ok": True, "done": True,
                               "rows": entry_c.rows,
                               "columns": entry_c.columns,
                               "source": state.source,
                               "chunks": len(entry_c.chunks)})
        self._finish(qid, state, streamed)

    def _finish(self, qid: str, state: _QueryState, streamed: int,
                error: Optional[str] = None) -> None:
        with self._lock:
            self._queries.pop(qid, None)
        registry().inc("gateway_queries_total")
        if error is not None:
            registry().inc("gateway_errors_total")
        if subscribers_active():
            rows = state.cached.rows if (error is None
                                         and state.cached is not None) else 0
            notify("on_gateway_query", GatewayQueryRecord(
                query_id=qid, tenant=state.tenant,
                seconds=time.perf_counter() - state.t0, rows=rows,
                source=state.source, bytes_streamed=streamed,
                prepared_handle=state.handle, error=error))

    def _verb_cancel(self, conn, tenant: str, req: dict) -> None:
        qid, state = self._state_for(tenant, req)
        delivered = state.future.cancel() if state.future is not None else False
        proto.send_json(conn, {"ok": True, "cancelled": delivered})

    def _verb_stats(self, conn, tenant: str, req: dict) -> None:
        snap = registry().snapshot()
        proto.send_json(conn, {
            "ok": True,
            "metrics": {k: v for k, v in snap.items()
                        if k.startswith(("gateway_", "result_cache_",
                                         "serve_"))},
            "result_cache": self.cache.stats(),
            "tenants": self._session.tenant_stats(),
        })
