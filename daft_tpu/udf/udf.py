"""User-defined functions.

Reference parity: daft/udf/udf_v2.py:52 (`@daft.func` Func dataclass: is_async,
is_batch, batch_size, use_process, max_concurrency) and daft/udf/legacy.py
(`@daft.udf` batch UDFs). Row-wise funcs receive python values; batch funcs receive
Series and return Series/arrays.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, List, Optional

from ..datatype import DataType


@dataclasses.dataclass
class Func:
    fn: Callable
    return_dtype: DataType
    is_batch: bool = False
    is_async: bool = False
    is_generator: bool = False
    batch_size: Optional[int] = None
    max_concurrency: Optional[int] = None
    use_process: bool = False
    name: str = "udf"
    # prefix-affinity routing for replicated stateful operators (vLLM-style):
    # rows sharing the first N chars of the first argument go to one replica
    route_prefix_len: Optional[int] = None
    # ---- device-UDF tier (ops/udf_stage.py) --------------------------------
    # on_device=True marks `fn` as a jax-traceable BATCH function with the
    # signature ``fn(params, *arrays) -> array`` (row-aligned output). The
    # executor lowers it to a DeviceUdfProject stage: weights resident in HBM
    # via the residency manager, morsels coalesced into super-batches, one
    # compiled dispatch per super-batch. batch_size caps the dispatch bucket.
    on_device: bool = False
    # () -> numpy pytree of model weights, called once per worker process;
    # the tier registers the pytree in the residency manager under a content
    # fingerprint of the weight bytes. None = stateless fn (params is None).
    device_params: Optional[Callable] = None
    # True: device_params() returns a dict whose TOP-LEVEL entries anchor
    # independently in the residency manager — parts shared between Funcs
    # (e.g. one encoder under both embed and every classify label set)
    # resolve to a single HBM entry and upload once per process total.
    device_params_split: bool = False
    # host preprocess per morsel (tokenization): (*arg_pylists) -> tuple of
    # row-aligned numpy arrays fed to `fn`. None = each arg Series' to_numpy.
    device_prepare: Optional[Callable] = None
    # host postprocess: (np_out_rows) -> list of python values (e.g. label
    # strings from argmax codes). None = rows of the output array as-is.
    device_finish: Optional[Callable] = None
    # stable fingerprint for the jit-program cache and cost-decision cache;
    # None derives one from fn.__module__/__qualname__ (process-local only).
    device_key: Optional[str] = None

    @property
    def is_device(self) -> bool:
        return self.on_device

    def __getstate__(self):
        # the weight-anchor cache (ops/udf_stage.py) holds the model's host
        # pytree: process-local, rebuilt lazily per worker — shipping it in
        # every pickled plan blob would move the whole model per task
        state = dict(self.__dict__)
        state.pop("_weight_anchor_cache", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    def __call__(self, *args, **kwargs):
        from .expr import UdfCall
        from ..expressions.expressions import Expression, lit

        exprs = [a if isinstance(a, Expression) else lit(a) for a in args]
        return UdfCall(self, exprs, kwargs)


def func(
    fn: Optional[Callable] = None,
    *,
    return_dtype: Optional[DataType] = None,
    is_batch: bool = False,
    batch_size: Optional[int] = None,
    max_concurrency: Optional[int] = None,
    use_process: bool = False,
    on_device: bool = False,
    device_params: Optional[Callable] = None,
    device_prepare: Optional[Callable] = None,
    device_finish: Optional[Callable] = None,
    device_key: Optional[str] = None,
):
    """``@daft_tpu.func`` decorator — wrap a Python function as a scalar UDF.

    Row-wise by default; ``is_batch=True`` passes Series in / expects Series out.
    The return dtype is taken from ``return_dtype`` or inferred from the type hint.

    ``on_device=True`` marks a jax-traceable batch UDF ``fn(params, *arrays) ->
    array`` for the device-UDF tier (ops/udf_stage.py): weights from
    ``device_params()`` live in HBM under the residency manager, morsels
    coalesce into super-batches, and ``df.with_column(embed(col))`` becomes a
    first-class device stage with a semantics-identical host fallback.
    """

    def wrap(f: Callable) -> Func:
        rdt = return_dtype
        if rdt is None:
            hints = inspect.signature(f).return_annotation
            rdt = _dtype_from_hint(hints)
        return Func(
            fn=f,
            return_dtype=rdt,
            is_batch=is_batch or on_device,
            is_async=inspect.iscoroutinefunction(f),
            # batch fns return whole Series — generator semantics apply row-wise only
            is_generator=inspect.isgeneratorfunction(f) and not (is_batch or on_device),
            batch_size=batch_size,
            max_concurrency=max_concurrency,
            use_process=use_process,
            name=getattr(f, "__name__", "udf"),
            on_device=on_device,
            device_params=device_params,
            device_prepare=device_prepare,
            device_finish=device_finish,
            device_key=device_key,
        )

    if fn is not None:
        return wrap(fn)
    return wrap


def _dtype_from_hint(hint) -> DataType:
    import inspect as _i

    mapping = {
        int: DataType.int64(),
        float: DataType.float64(),
        str: DataType.string(),
        bool: DataType.bool(),
        bytes: DataType.binary(),
    }
    if hint in mapping:
        return mapping[hint]
    if hint is _i.Signature.empty or hint is None:
        raise ValueError(
            "UDF needs a return dtype: pass return_dtype= or annotate the function's return type"
        )
    # typing.List[int] etc.
    import typing

    origin = typing.get_origin(hint)
    if origin in (list, typing.List):
        (inner,) = typing.get_args(hint) or (None,)
        if inner in mapping:
            return DataType.list(mapping[inner])
    return DataType.python()


class _ClsWrapper:
    """Wraps a user class; calling it captures __init__ args and returns a
    lazy instance handle whose methods build UDF expressions."""

    def __init__(self, klass, max_concurrency: Optional[int], use_process: bool):
        self._klass = klass
        self._max_concurrency = max_concurrency
        self._use_process = use_process

    def __call__(self, *args, **kwargs) -> "_ClsInstance":
        return _ClsInstance(self, args, kwargs)


class _ClsInstance:
    """Deferred instance: the real object is constructed once per worker process
    on first use (expensive model loads happen on the executor, not the driver)."""

    def __init__(self, wrapper: _ClsWrapper, init_args, init_kwargs):
        object.__setattr__(self, "_wrapper", wrapper)
        object.__setattr__(self, "_init_args", init_args)
        object.__setattr__(self, "_init_kwargs", init_kwargs)
        object.__setattr__(self, "_obj", None)
        object.__setattr__(self, "_method_funcs", {})

    def _materialize(self):
        if self._obj is None:
            obj = self._wrapper._klass(*self._init_args, **self._init_kwargs)
            object.__setattr__(self, "_obj", obj)
        return self._obj

    def __getattr__(self, name: str):
        # guard against recursion during unpickling (cloudpickle reconstructs
        # the object before __dict__ exists, so the proxy's OWN internals must
        # fail fast here); user underscore-named methods still resolve
        if name.startswith("__"):
            raise AttributeError(name)
        try:
            method_funcs = object.__getattribute__(self, "_method_funcs")
            wrapper = object.__getattribute__(self, "_wrapper")
        except AttributeError:
            raise AttributeError(name) from None
        cached = method_funcs.get(name)
        if cached is not None:
            return cached
        target = getattr(wrapper._klass, name, None)
        if target is None:
            raise AttributeError(name)
        if not callable(target):
            # plain attribute / property: read it off the materialized instance
            return getattr(self._materialize(), name)
        rdt = getattr(target, "__udf_return_dtype__", None)
        if rdt is None:
            hint = inspect.signature(target).return_annotation
            try:
                rdt = _dtype_from_hint(hint)
            except ValueError:
                rdt = DataType.python()
        inst = self

        def bound(*vals, **kw):
            return getattr(inst._materialize(), name)(*vals, **kw)

        on_device = bool(getattr(target, "__udf_on_device__", False))
        device_params = None
        device_prepare = None
        device_finish = None
        if on_device:
            # device-UDF hooks resolve off the (lazily materialized) instance:
            # device_params() declares the weight pytree — the model loads once
            # per worker, exactly like any other @cls state — and the optional
            # device_prepare/device_finish methods do host tokenization and
            # output decoding around the jax-traceable method itself
            klass = self._wrapper._klass
            if getattr(klass, "device_params", None) is not None:
                def device_params():
                    return inst._materialize().device_params()
            if getattr(klass, "device_prepare", None) is not None:
                def device_prepare(*lists):
                    return inst._materialize().device_prepare(*lists)
            if getattr(klass, "device_finish", None) is not None:
                def device_finish(out):
                    return inst._materialize().device_finish(out)

        # the jit-program/cost-cache identity: every @cls method's `bound`
        # wrapper shares one code object, so the code-hash fallback would
        # collide two different classes' device methods onto one compiled
        # program — derive a key from the TARGET's class+method instead.
        # Instances of one class share the program deliberately: the traced
        # body is (self, params, *arrays) with all weights flowing through
        # params, so per-instance state must ride device_params().
        device_key = None
        if on_device:
            klass = self._wrapper._klass
            device_key = getattr(target, "__udf_device_key__", None) or \
                f"{klass.__module__}.{klass.__qualname__}.{name}"

        f = Func(
            fn=bound,
            return_dtype=rdt,
            is_batch=bool(getattr(target, "__udf_is_batch__", False)) or on_device,
            is_async=inspect.iscoroutinefunction(target),
            is_generator=inspect.isgeneratorfunction(target),
            max_concurrency=self._wrapper._max_concurrency,
            use_process=self._wrapper._use_process,
            name=f"{self._wrapper._klass.__name__}.{name}",
            on_device=on_device,
            device_params=device_params,
            device_prepare=device_prepare,
            device_finish=device_finish,
            device_key=device_key,
            batch_size=getattr(target, "__udf_batch_size__", None),
        )
        self._method_funcs[name] = f
        return f


def cls(klass=None, *, max_concurrency: Optional[int] = None, use_process: bool = False):
    """``@daft_tpu.cls`` — stateful UDF class; instantiated once per worker.

    Reference parity: daft/udf/udf_v2.py ClsBase::

        @daft_tpu.cls
        class Embedder:
            def __init__(self, model): self.m = load(model)
            def embed(self, text: str) -> float: ...

        e = Embedder("small")                 # lazy — nothing loads here
        df.select(e.embed(col("text")))       # loads once per worker
    """
    if klass is not None:
        return _ClsWrapper(klass, max_concurrency, use_process)

    def wrap(k):
        return _ClsWrapper(k, max_concurrency, use_process)

    return wrap


def method(fn: Optional[Callable] = None, *, return_dtype: Optional[DataType] = None,
           is_batch: bool = False, on_device: bool = False,
           batch_size: Optional[int] = None, device_key: Optional[str] = None):
    """Mark a method of a ``@cls`` class as a UDF entrypoint with an explicit
    return dtype (otherwise inferred from the annotation).

    ``on_device=True`` marks the method jax-traceable — signature
    ``(self, params, *arrays) -> array`` — and routes it through the
    device-UDF tier; the class's ``device_params()`` hook declares the weight
    pytree (optional ``device_prepare``/``device_finish`` do host
    tokenization/decoding). ``batch_size`` caps the dispatch bucket;
    ``device_key`` overrides the program-cache identity (defaults to the
    class's module.qualname.method — instances share one compiled program,
    so per-instance state must flow through ``device_params()``)."""

    def wrap(f):
        f.__udf_method__ = True
        f.__udf_return_dtype__ = return_dtype
        f.__udf_is_batch__ = is_batch
        f.__udf_on_device__ = on_device
        f.__udf_batch_size__ = batch_size
        f.__udf_device_key__ = device_key
        return f

    if fn is not None:
        return wrap(fn)
    return wrap


def udf(*, return_dtype: DataType, batch_size: Optional[int] = None,
        max_concurrency: Optional[int] = None, use_process: bool = False):
    """Legacy ``@daft.udf`` decorator (reference: daft/udf/legacy.py) — batch
    UDFs receiving Series arguments."""

    def wrap(f: Callable) -> Func:
        return Func(
            fn=f,
            return_dtype=return_dtype,
            is_batch=True,
            batch_size=batch_size,
            max_concurrency=max_concurrency,
            use_process=use_process,
            name=getattr(f, "__name__", "udf"),
        )

    return wrap
