"""User-defined functions.

Reference parity: daft/udf/udf_v2.py:52 (`@daft.func` Func dataclass: is_async,
is_batch, batch_size, use_process, max_concurrency) and daft/udf/legacy.py
(`@daft.udf` batch UDFs). Row-wise funcs receive python values; batch funcs receive
Series and return Series/arrays.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, List, Optional

from ..datatype import DataType


@dataclasses.dataclass
class Func:
    fn: Callable
    return_dtype: DataType
    is_batch: bool = False
    is_async: bool = False
    batch_size: Optional[int] = None
    max_concurrency: Optional[int] = None
    use_process: bool = False
    name: str = "udf"

    def __call__(self, *args, **kwargs):
        from .expr import UdfCall
        from ..expressions.expressions import Expression, lit

        exprs = [a if isinstance(a, Expression) else lit(a) for a in args]
        return UdfCall(self, exprs, kwargs)


def func(
    fn: Optional[Callable] = None,
    *,
    return_dtype: Optional[DataType] = None,
    is_batch: bool = False,
    batch_size: Optional[int] = None,
    max_concurrency: Optional[int] = None,
    use_process: bool = False,
):
    """``@daft_tpu.func`` decorator — wrap a Python function as a scalar UDF.

    Row-wise by default; ``is_batch=True`` passes Series in / expects Series out.
    The return dtype is taken from ``return_dtype`` or inferred from the type hint.
    """

    def wrap(f: Callable) -> Func:
        rdt = return_dtype
        if rdt is None:
            hints = inspect.signature(f).return_annotation
            rdt = _dtype_from_hint(hints)
        return Func(
            fn=f,
            return_dtype=rdt,
            is_batch=is_batch,
            is_async=inspect.iscoroutinefunction(f),
            batch_size=batch_size,
            max_concurrency=max_concurrency,
            use_process=use_process,
            name=getattr(f, "__name__", "udf"),
        )

    if fn is not None:
        return wrap(fn)
    return wrap


def _dtype_from_hint(hint) -> DataType:
    import inspect as _i

    mapping = {
        int: DataType.int64(),
        float: DataType.float64(),
        str: DataType.string(),
        bool: DataType.bool(),
        bytes: DataType.binary(),
    }
    if hint in mapping:
        return mapping[hint]
    if hint is _i.Signature.empty or hint is None:
        raise ValueError(
            "UDF needs a return dtype: pass return_dtype= or annotate the function's return type"
        )
    # typing.List[int] etc.
    import typing

    origin = typing.get_origin(hint)
    if origin in (list, typing.List):
        (inner,) = typing.get_args(hint) or (None,)
        if inner in mapping:
            return DataType.list(mapping[inner])
    return DataType.python()


class cls:  # noqa: N801 — mirrors the reference's @daft.cls decorator name
    """``@daft_tpu.cls`` — stateful UDF class; instantiated once per worker.

    Reference parity: daft/udf/udf_v2.py ClsBase. The wrapped class's __init__ runs
    lazily on first call (per process), so expensive setup (model load) happens on
    the executor, not the driver.
    """

    def __init__(self, klass=None, *, max_concurrency: Optional[int] = None, use_process: bool = False):
        self._klass = klass
        self._max_concurrency = max_concurrency
        self._use_process = use_process
        self._instance = None

    def __call__(self, *args, **kwargs):
        if self._klass is None:
            # used as @cls(...) with arguments
            self._klass = args[0]
            return self
        raise TypeError("instantiate via .method(...) expressions")


def method(fn: Optional[Callable] = None, *, return_dtype: Optional[DataType] = None):
    """Mark a method of a ``@cls`` class as a UDF entrypoint."""

    def wrap(f):
        f.__udf_method__ = True
        f.__udf_return_dtype__ = return_dtype
        return f

    if fn is not None:
        return wrap(fn)
    return wrap
