"""User-defined functions.

Reference parity: daft/udf/udf_v2.py:52 (`@daft.func` Func dataclass: is_async,
is_batch, batch_size, use_process, max_concurrency) and daft/udf/legacy.py
(`@daft.udf` batch UDFs). Row-wise funcs receive python values; batch funcs receive
Series and return Series/arrays.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, List, Optional

from ..datatype import DataType


@dataclasses.dataclass
class Func:
    fn: Callable
    return_dtype: DataType
    is_batch: bool = False
    is_async: bool = False
    is_generator: bool = False
    batch_size: Optional[int] = None
    max_concurrency: Optional[int] = None
    use_process: bool = False
    name: str = "udf"
    # prefix-affinity routing for replicated stateful operators (vLLM-style):
    # rows sharing the first N chars of the first argument go to one replica
    route_prefix_len: Optional[int] = None

    def __call__(self, *args, **kwargs):
        from .expr import UdfCall
        from ..expressions.expressions import Expression, lit

        exprs = [a if isinstance(a, Expression) else lit(a) for a in args]
        return UdfCall(self, exprs, kwargs)


def func(
    fn: Optional[Callable] = None,
    *,
    return_dtype: Optional[DataType] = None,
    is_batch: bool = False,
    batch_size: Optional[int] = None,
    max_concurrency: Optional[int] = None,
    use_process: bool = False,
):
    """``@daft_tpu.func`` decorator — wrap a Python function as a scalar UDF.

    Row-wise by default; ``is_batch=True`` passes Series in / expects Series out.
    The return dtype is taken from ``return_dtype`` or inferred from the type hint.
    """

    def wrap(f: Callable) -> Func:
        rdt = return_dtype
        if rdt is None:
            hints = inspect.signature(f).return_annotation
            rdt = _dtype_from_hint(hints)
        return Func(
            fn=f,
            return_dtype=rdt,
            is_batch=is_batch,
            is_async=inspect.iscoroutinefunction(f),
            # batch fns return whole Series — generator semantics apply row-wise only
            is_generator=inspect.isgeneratorfunction(f) and not is_batch,
            batch_size=batch_size,
            max_concurrency=max_concurrency,
            use_process=use_process,
            name=getattr(f, "__name__", "udf"),
        )

    if fn is not None:
        return wrap(fn)
    return wrap


def _dtype_from_hint(hint) -> DataType:
    import inspect as _i

    mapping = {
        int: DataType.int64(),
        float: DataType.float64(),
        str: DataType.string(),
        bool: DataType.bool(),
        bytes: DataType.binary(),
    }
    if hint in mapping:
        return mapping[hint]
    if hint is _i.Signature.empty or hint is None:
        raise ValueError(
            "UDF needs a return dtype: pass return_dtype= or annotate the function's return type"
        )
    # typing.List[int] etc.
    import typing

    origin = typing.get_origin(hint)
    if origin in (list, typing.List):
        (inner,) = typing.get_args(hint) or (None,)
        if inner in mapping:
            return DataType.list(mapping[inner])
    return DataType.python()


class _ClsWrapper:
    """Wraps a user class; calling it captures __init__ args and returns a
    lazy instance handle whose methods build UDF expressions."""

    def __init__(self, klass, max_concurrency: Optional[int], use_process: bool):
        self._klass = klass
        self._max_concurrency = max_concurrency
        self._use_process = use_process

    def __call__(self, *args, **kwargs) -> "_ClsInstance":
        return _ClsInstance(self, args, kwargs)


class _ClsInstance:
    """Deferred instance: the real object is constructed once per worker process
    on first use (expensive model loads happen on the executor, not the driver)."""

    def __init__(self, wrapper: _ClsWrapper, init_args, init_kwargs):
        object.__setattr__(self, "_wrapper", wrapper)
        object.__setattr__(self, "_init_args", init_args)
        object.__setattr__(self, "_init_kwargs", init_kwargs)
        object.__setattr__(self, "_obj", None)
        object.__setattr__(self, "_method_funcs", {})

    def _materialize(self):
        if self._obj is None:
            obj = self._wrapper._klass(*self._init_args, **self._init_kwargs)
            object.__setattr__(self, "_obj", obj)
        return self._obj

    def __getattr__(self, name: str):
        # guard against recursion during unpickling (cloudpickle reconstructs
        # the object before __dict__ exists, so the proxy's OWN internals must
        # fail fast here); user underscore-named methods still resolve
        if name.startswith("__"):
            raise AttributeError(name)
        try:
            method_funcs = object.__getattribute__(self, "_method_funcs")
            wrapper = object.__getattribute__(self, "_wrapper")
        except AttributeError:
            raise AttributeError(name) from None
        cached = method_funcs.get(name)
        if cached is not None:
            return cached
        target = getattr(wrapper._klass, name, None)
        if target is None:
            raise AttributeError(name)
        if not callable(target):
            # plain attribute / property: read it off the materialized instance
            return getattr(self._materialize(), name)
        rdt = getattr(target, "__udf_return_dtype__", None)
        if rdt is None:
            hint = inspect.signature(target).return_annotation
            try:
                rdt = _dtype_from_hint(hint)
            except ValueError:
                rdt = DataType.python()
        inst = self

        def bound(*vals, **kw):
            return getattr(inst._materialize(), name)(*vals, **kw)

        f = Func(
            fn=bound,
            return_dtype=rdt,
            is_batch=bool(getattr(target, "__udf_is_batch__", False)),
            is_async=inspect.iscoroutinefunction(target),
            is_generator=inspect.isgeneratorfunction(target),
            max_concurrency=self._wrapper._max_concurrency,
            use_process=self._wrapper._use_process,
            name=f"{self._wrapper._klass.__name__}.{name}",
        )
        self._method_funcs[name] = f
        return f


def cls(klass=None, *, max_concurrency: Optional[int] = None, use_process: bool = False):
    """``@daft_tpu.cls`` — stateful UDF class; instantiated once per worker.

    Reference parity: daft/udf/udf_v2.py ClsBase::

        @daft_tpu.cls
        class Embedder:
            def __init__(self, model): self.m = load(model)
            def embed(self, text: str) -> float: ...

        e = Embedder("small")                 # lazy — nothing loads here
        df.select(e.embed(col("text")))       # loads once per worker
    """
    if klass is not None:
        return _ClsWrapper(klass, max_concurrency, use_process)

    def wrap(k):
        return _ClsWrapper(k, max_concurrency, use_process)

    return wrap


def method(fn: Optional[Callable] = None, *, return_dtype: Optional[DataType] = None,
           is_batch: bool = False):
    """Mark a method of a ``@cls`` class as a UDF entrypoint with an explicit
    return dtype (otherwise inferred from the annotation)."""

    def wrap(f):
        f.__udf_method__ = True
        f.__udf_return_dtype__ = return_dtype
        f.__udf_is_batch__ = is_batch
        return f

    if fn is not None:
        return wrap(fn)
    return wrap


def udf(*, return_dtype: DataType, batch_size: Optional[int] = None,
        max_concurrency: Optional[int] = None, use_process: bool = False):
    """Legacy ``@daft.udf`` decorator (reference: daft/udf/legacy.py) — batch
    UDFs receiving Series arguments."""

    def wrap(f: Callable) -> Func:
        return Func(
            fn=f,
            return_dtype=return_dtype,
            is_batch=True,
            batch_size=batch_size,
            max_concurrency=max_concurrency,
            use_process=use_process,
            name=getattr(f, "__name__", "udf"),
        )

    return wrap
