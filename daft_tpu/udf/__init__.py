import sys
import types

from .udf import Func, cls, func, method, udf
from .expr import UdfCall

__all__ = ["Func", "cls", "func", "method", "udf", "UdfCall"]


class _CallableModule(types.ModuleType):
    """`daft_tpu.udf(...)` works even though `daft_tpu.udf` is also this package
    (import machinery shadows the api-level function with the module)."""

    def __call__(self, *args, **kwargs):
        return udf(*args, **kwargs)


sys.modules[__name__].__class__ = _CallableModule
