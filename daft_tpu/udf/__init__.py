from .udf import Func, func, method
from .expr import UdfCall

__all__ = ["Func", "func", "method", "UdfCall"]
