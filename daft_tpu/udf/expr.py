"""UDF call expression node.

Reference parity: src/daft-dsl/src/functions/python (ScalarFn python UDF exprs);
the SplitUDFs optimizer rule isolates these into their own UDFProject plan nodes so
device-stage fusion is never broken by opaque Python (SURVEY.md §7 'hard parts').
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List

from ..core.series import Series
from ..datatype import Field
from ..expressions.expressions import Expression
from ..schema import Schema


class UdfCall(Expression):
    def __init__(self, func, args: List[Expression], kwargs: Dict[str, Any]):
        self.func = func
        self.args = args
        self.kwargs = kwargs

    def name(self) -> str:
        return self.args[0].name() if self.args else self.func.name

    def children(self) -> List[Expression]:
        return list(self.args)

    def with_children(self, children):
        return UdfCall(self.func, children, self.kwargs)

    def to_field(self, schema: Schema) -> Field:
        return Field(self.name(), self.func.return_dtype)

    def __repr__(self):
        inner = ", ".join(repr(a) for a in self.args)
        return f"udf:{self.func.name}({inner})"

    # ---- execution ------------------------------------------------------------------
    def eval_host(self, arg_series: List[Series], num_rows: int) -> Series:
        f = self.func
        if f.is_batch:
            out = f.fn(*arg_series, **self.kwargs)
            if not isinstance(out, Series):
                out = Series.from_pylist(list(out), f.name, f.return_dtype)
            return out.rename(self.name())

        cols = [s.to_pylist() for s in arg_series]
        # broadcast length-1 args
        cols = [c * num_rows if len(c) == 1 and num_rows != 1 else c for c in cols]
        if f.is_async:
            async def run_all():
                return await asyncio.gather(*(f.fn(*vals, **self.kwargs) for vals in zip(*cols)))

            results = asyncio.run(run_all())
        else:
            results = [f.fn(*vals, **self.kwargs) for vals in zip(*cols)]
        return Series.from_pylist(results, self.name(), f.return_dtype)
