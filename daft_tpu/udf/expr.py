"""UDF call expression node.

Reference parity: src/daft-dsl/src/functions/python (ScalarFn python UDF exprs);
the SplitUDFs optimizer rule isolates these into their own UDFProject plan nodes so
device-stage fusion is never broken by opaque Python (SURVEY.md §7 'hard parts').

Execution tiers (reference: intermediate_ops/udf.rs:384 thread-vs-process pick +
streaming_sink/async_udf.rs):
- in-thread (default): row loop / batch call under the GIL
- process pool (use_process=True): forked workers via execution/udf_process.py
- async: coroutine fan-out with a max_concurrency semaphore
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List

from ..core.series import Series
from ..datatype import DataType, Field
from ..expressions.expressions import Expression
from ..schema import Schema


class UdfCall(Expression):
    def __init__(self, func, args: List[Expression], kwargs: Dict[str, Any]):
        self.func = func
        self.args = args
        self.kwargs = kwargs

    def name(self) -> str:
        return self.args[0].name() if self.args else self.func.name

    def children(self) -> List[Expression]:
        return list(self.args)

    def with_children(self, children):
        return UdfCall(self.func, children, self.kwargs)

    def to_field(self, schema: Schema) -> Field:
        dt = self.func.return_dtype
        if getattr(self.func, "is_generator", False):
            dt = DataType.list(dt)
        return Field(self.name(), dt)

    def __repr__(self):
        inner = ", ".join(repr(a) for a in self.args)
        return f"udf:{self.func.name}({inner})"

    # ---- execution ------------------------------------------------------------------
    def eval_host(self, arg_series: List[Series], num_rows: int) -> Series:
        f = self.func
        out_name = self.name()

        if getattr(f, "on_device", False):
            # device Func on the host path (device_mode off / cost model chose
            # host / non-isolated expression): the same prepare -> jit program
            # -> finish pipeline, run eagerly per batch with no stage,
            # coalescer, or pin scope — semantics identical to the tier
            if self.kwargs:
                # the device contract is positional arrays only; silently
                # dropping kwargs here would run fn without them and produce
                # wrong results with no error
                raise TypeError(
                    f"device UDF {f.name!r} does not accept keyword "
                    f"arguments (got {sorted(self.kwargs)}); the contract is "
                    f"fn(params, *arrays)")
            from ..ops.udf_stage import host_eval_device_func

            vals = host_eval_device_func(f, arg_series, num_rows)
            return Series.from_pylist(vals, out_name, f.return_dtype)

        if f.use_process:
            from ..execution.udf_process import get_pool

            pool = get_pool(f)
            if f.route_prefix_len is not None:
                payload = pool.run_batch_routed(arg_series, self.kwargs, num_rows,
                                                f.route_prefix_len)
            else:
                payload = pool.run_batch(arg_series, self.kwargs, num_rows)
            if f.is_batch:
                out = Series.from_arrow(payload, out_name)
                if out.dtype != f.return_dtype:
                    out = out.cast(f.return_dtype)
                return out
            dt = DataType.list(f.return_dtype) if f.is_generator else f.return_dtype
            return Series.from_pylist(payload, out_name, dt)

        if f.is_batch:
            out = f.fn(*arg_series, **self.kwargs)
            if not isinstance(out, Series):
                out = Series.from_pylist(list(out), f.name, f.return_dtype)
            return out.rename(out_name)

        cols = [s.to_pylist() for s in arg_series]
        # broadcast length-1 args
        cols = [c * num_rows if len(c) == 1 and num_rows != 1 else c for c in cols]

        if getattr(f, "is_generator", False):
            results = [list(f.fn(*vals, **self.kwargs)) for vals in zip(*cols)]
            return Series.from_pylist(results, out_name, DataType.list(f.return_dtype))

        if f.is_async:
            limit = f.max_concurrency or 256

            async def run_all():
                sem = asyncio.Semaphore(limit)

                async def one(vals):
                    async with sem:
                        return await f.fn(*vals, **self.kwargs)

                return await asyncio.gather(*(one(vals) for vals in zip(*cols)))

            results = asyncio.run(run_all())
        else:
            results = [f.fn(*vals, **self.kwargs) for vals in zip(*cols)]
        return Series.from_pylist(results, out_name, f.return_dtype)
