"""Top-level user API re-exports (DataFrame, col, lit, read_* functions).

Populated as the API surface lands; daft_tpu/__init__.py lazily forwards here.
"""
