"""Top-level user API re-exports (DataFrame, col, lit, from_*/read_* functions).

daft_tpu/__init__.py lazily forwards attribute access here.
Reference parity: daft/__init__.py + daft/convert.py + daft/io/__init__.py:19-37.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from .config import execution_config, execution_config_ctx, set_execution_config
from .core.micropartition import MicroPartition
from .dataframe import DataFrame, GroupedDataFrame
from .expressions import Expression, col, lit
from .checkpoint import CheckpointStore, FileCheckpointStore, MemoryCheckpointStore
from .io.io_config import HTTPConfig, IOConfig, S3Config, io_config, set_io_config
from .io.sink import DataSink, WriteResult
from .io.source import DataSource, DataSourceTask
from .plan.builder import LogicalPlanBuilder
from .schema import Schema
from .udf import Func, cls, func, method, udf
from .window import Window
from . import functions

__all__ = [
    "DataFrame", "GroupedDataFrame", "Expression", "col", "lit", "element", "func",
    "from_pydict", "from_pylist", "from_arrow", "from_pandas",
    "read_parquet", "read_csv", "read_json", "from_glob_path", "sql", "sql_expr",
    "cls", "method", "udf", "Func",
    "launch_dashboard", "enable_event_log", "serving_session",
]


# ---- observability conveniences ------------------------------------------------------


def launch_dashboard(host: str = "127.0.0.1", port: int = 0):
    """Start the embedded dashboard (query history UI, /api/* JSON, a
    Prometheus /metrics exposition, and per-query Chrome-trace downloads at
    /api/query/<id>/trace); returns the Dashboard (``.url``, ``.shutdown()``).
    Reference: daft.subscribers.dashboard.launch."""
    from .observability.dashboard import launch

    return launch(host, port)


def enable_event_log(path: str):
    """Append one JSON line per query lifecycle event to `path` (see
    observability/event_log.py, schema_version documented there); returns the
    subscriber for observability.event_log.disable_event_log."""
    from .observability.event_log import enable_event_log as _enable

    return _enable(path)


def serving_session(max_concurrent: Optional[int] = None, runner=None,
                    prepared_cap: int = 64):
    """Open a ServingSession: N concurrent queries with fair per-tenant
    admission, an HBM admission controller, and a prepared-query cache
    (daft_tpu/serving/). Use as a context manager:

        with daft_tpu.serving_session(max_concurrent=4) as sess:
            fut = sess.submit(df.groupby("k").agg(...), tenant="acme")
            rows = fut.to_pydict()
    """
    from .serving import ServingSession

    return ServingSession(max_concurrent=max_concurrent, runner=runner,
                          prepared_cap=prepared_cap)


def element() -> Expression:
    """Placeholder for the current list element in list.map-style expressions."""
    return col("")


# ---- in-memory constructors ----------------------------------------------------------


def from_pydict(data: Dict[str, Any]) -> DataFrame:
    part = MicroPartition.from_pydict(data)
    return DataFrame(LogicalPlanBuilder.from_in_memory(part.schema, [part]))


def from_pylist(rows: List[dict]) -> DataFrame:
    keys: List[str] = []
    for r in rows:
        for k in r:
            if k not in keys:
                keys.append(k)
    return from_pydict({k: [r.get(k) for r in rows] for k in keys})


def from_arrow(tables) -> DataFrame:
    if not isinstance(tables, (list, tuple)):
        tables = [tables]
    parts = [MicroPartition.from_arrow(t) for t in tables]
    return DataFrame(LogicalPlanBuilder.from_in_memory(parts[0].schema, list(parts)))


def from_pandas(dfs) -> DataFrame:
    import pyarrow as pa

    if not isinstance(dfs, (list, tuple)):
        dfs = [dfs]
    return from_arrow([pa.Table.from_pandas(d) for d in dfs])


def _from_partitions(parts: List[MicroPartition], schema: Schema) -> DataFrame:
    return DataFrame(LogicalPlanBuilder.from_in_memory(schema, parts))


# ---- file readers --------------------------------------------------------------------


def read_parquet(path: Union[str, List[str]], **options) -> DataFrame:
    from .io.parquet import ParquetScanOperator

    return DataFrame(LogicalPlanBuilder.from_scan(ParquetScanOperator(path, **options)))


def read_csv(path: Union[str, List[str]], **options) -> DataFrame:
    from .io.csv import CsvScanOperator

    return DataFrame(LogicalPlanBuilder.from_scan(CsvScanOperator(path, **options)))


def read_json(path: Union[str, List[str]], **options) -> DataFrame:
    from .io.json import JsonScanOperator

    return DataFrame(LogicalPlanBuilder.from_scan(JsonScanOperator(path, **options)))


def read_text(path: Union[str, List[str]], **options) -> DataFrame:
    """Line-oriented text files (one string column 'text'; .gz supported)."""
    from .io.text import TextScanOperator

    return DataFrame(LogicalPlanBuilder.from_scan(TextScanOperator(path, **options)))


def read_warc(path: Union[str, List[str]], **options) -> DataFrame:
    """WARC (Common Crawl) archives: one row per record (.gz supported)."""
    from .io.warc import WarcScanOperator

    return DataFrame(LogicalPlanBuilder.from_scan(WarcScanOperator(path, **options)))


def read_iceberg(table_path: str, snapshot_id: "Optional[int]" = None) -> DataFrame:
    """Read an Apache Iceberg table (v1/v2 metadata; Avro manifests parsed
    natively — io/iceberg.py). Identity partition pruning and parquet
    predicate/column pushdowns apply through the optimizer."""
    from .io.iceberg import IcebergScanOperator

    return DataFrame(LogicalPlanBuilder.from_scan(
        IcebergScanOperator(table_path, snapshot_id=snapshot_id)))


def read_deltalake(table_path: str) -> DataFrame:
    """Read a Delta Lake table (_delta_log JSON replay + parquet checkpoints —
    io/delta.py). Partition/stats pruning applies through the optimizer;
    partition columns are reconstructed from the log."""
    from .io.delta import DeltaScanOperator

    return DataFrame(LogicalPlanBuilder.from_scan(DeltaScanOperator(table_path)))


read_delta_lake = read_deltalake


def read_sql(sql_query: str, connection, partition_col=None,
             num_partitions: int = 1) -> DataFrame:
    """Read the result of a SQL query over a DB-API connection (reference:
    daft.read_sql); stdlib sqlite3 works out of the box."""
    from .io.sql_writer import read_sql as _read

    return _read(sql_query, connection, partition_col, num_partitions)


def read_hudi(table_path: str) -> DataFrame:
    """Read an Apache Hudi copy-on-write table (timeline replay + latest
    file slices per file group — io/hudi.py; reference: daft/io/hudi)."""
    from .io.hudi import HudiScanOperator

    return DataFrame(LogicalPlanBuilder.from_scan(HudiScanOperator(table_path)))


def from_glob_path(path: str) -> DataFrame:
    from .io.glob_files import GlobPathScanOperator

    return DataFrame(LogicalPlanBuilder.from_scan(GlobPathScanOperator(path)))


# ---- SQL -----------------------------------------------------------------------------


def sql(query: str, **bindings) -> DataFrame:
    from .sql import sql as _sql

    return _sql(query, **bindings)


def sql_expr(text: str) -> Expression:
    from .sql import sql_expr as _sql_expr

    return _sql_expr(text)


def load_extension(path: str):
    """Load a native extension module (stable C ABI over the Arrow C Data
    Interface — see native/include/daft_tpu_ext.h) and register its scalar
    functions (reference: daft-ext module loading)."""
    from .ext import load_extension as _load

    return _load(path)


def call_function(name: str, *args, **kwargs) -> Expression:
    """Call a registered scalar function (built-in or extension-provided) as
    an expression."""
    from .expressions.expressions import Function
    from .plan.builder import _to_expr

    return Function(name, [_to_expr(a) for a in args], kwargs or None)


def file(path_expr, io_config=None) -> Expression:
    """Build a lazy File column from path/URL strings (reference:
    daft.functions.file)."""
    from .plan.builder import _to_expr

    return _to_expr(path_expr)._fn("file", io_config=io_config)


def from_files(path: str, io_config=None) -> DataFrame:
    """List files matching a glob into a DataFrame with lazy File references
    (reference: daft.from_files — path/size columns + a file handle column).
    Columns: path (string), size (int64), file (File)."""
    from .expressions import col as _col

    df = from_glob_path(path)
    return df.with_columns({
        "file": file(_col("path"), io_config=io_config),
    })


def read_lance(uri: str, **kwargs) -> DataFrame:
    """Read a Lance dataset (requires the `lance` package, like the
    reference's daft.read_lance)."""
    try:
        import lance
    except ImportError as e:
        raise ImportError("read_lance requires the 'lance' package "
                          "(pip install pylance)") from e
    ds = lance.dataset(uri, **kwargs)
    return from_arrow(ds.to_table())
