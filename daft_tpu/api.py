"""Top-level user API re-exports (DataFrame, col, lit, read_* functions).

daft_tpu/__init__.py lazily forwards attribute access here.
"""

from .expressions import Expression, col, lit
from .udf import func

__all__ = ["Expression", "col", "lit", "func"]
