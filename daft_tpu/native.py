"""ctypes loader for the C++ host-kernel library (native/src/kernels.cpp).

Reference parity: the reference compiles its Rust core into the daft.daft
extension module; here the hot host kernels live in a C ABI shared library with
a graceful numpy fallback when the library hasn't been built. Build:

    cmake -S native -B native/build && cmake --build native/build

The build drops libdaft_native.so into daft_tpu/_native/.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SO_PATH = os.path.join(_REPO_ROOT, "daft_tpu", "_native", "libdaft_native.so")


def _try_build() -> None:
    """Best-effort one-shot build if a toolchain is available (dev convenience)."""
    src_dir = os.path.join(_REPO_ROOT, "native")
    if not os.path.isdir(src_dir):
        return
    try:
        os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             os.path.join(src_dir, "src", "kernels.cpp"), "-o", _SO_PATH],
            check=True, capture_output=True, timeout=120,
        )
    except Exception:  # lint: ignore[broad-except] -- native kernels are optional acceleration;
        pass  # get_lib() returns None and every caller has a python path


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("DAFT_TPU_DISABLE_NATIVE"):
        return None
    src = os.path.join(_REPO_ROOT, "native", "src", "kernels.cpp")
    stale = (
        os.path.exists(_SO_PATH) and os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(_SO_PATH)
    )
    if not os.path.exists(_SO_PATH) or stale:
        _try_build()
    if not os.path.exists(_SO_PATH):
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    i64p = ctypes.POINTER(ctypes.c_int64)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)

    lib.xxhash64.restype = ctypes.c_uint64
    lib.xxhash64.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64]
    lib.hash_binary_column.restype = None
    lib.hash_binary_column.argtypes = [u8p, i64p, ctypes.c_int64, ctypes.c_uint64, u64p]
    lib.hash_u64_column.restype = None
    lib.hash_u64_column.argtypes = [u64p, ctypes.c_int64, ctypes.c_uint64, u64p]
    lib.factorize_i64.restype = ctypes.c_int64
    lib.factorize_i64.argtypes = [i64p, ctypes.c_int64, i64p]
    lib.combine_factorize_i64.restype = ctypes.c_int64
    lib.combine_factorize_i64.argtypes = [i64p, i64p, ctypes.c_int64, ctypes.c_int64, i64p]
    lib.grouped_sum_f64.restype = None
    lib.grouped_sum_f64.argtypes = [i64p, f64p, u8p, ctypes.c_int64, ctypes.c_int64, f64p, i64p]
    lib.grouped_sum_i64.restype = None
    lib.grouped_sum_i64.argtypes = [i64p, i64p, u8p, ctypes.c_int64, ctypes.c_int64, i64p, i64p]
    lib.grouped_minmax_f64.restype = None
    lib.grouped_minmax_f64.argtypes = [i64p, f64p, u8p, ctypes.c_int64, ctypes.c_int64, f64p, f64p]
    lib.grouped_minmax_i64.restype = None
    lib.grouped_minmax_i64.argtypes = [i64p, i64p, u8p, ctypes.c_int64, ctypes.c_int64, i64p, i64p]
    lib.join_count.restype = ctypes.c_int64
    lib.join_count.argtypes = [i64p, ctypes.c_int64, i64p, ctypes.c_int64, ctypes.c_int64, i64p, i64p]
    lib.join_fill.restype = None
    lib.join_fill.argtypes = [i64p, ctypes.c_int64, i64p, ctypes.c_int64, ctypes.c_int64,
                              i64p, i64p, i64p, i64p]
    lib.probe_count.restype = ctypes.c_int64
    lib.probe_count.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p, i64p]
    lib.i64_pairmap_build.restype = None
    lib.i64_pairmap_build.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p]
    lib.i64_pairmap_lookup.restype = None
    lib.i64_pairmap_lookup.argtypes = [i64p, ctypes.c_int64, i64p, ctypes.c_int64, i64p]
    lib.probe_lookup_count_pair.restype = ctypes.c_int64
    lib.probe_lookup_count_pair.argtypes = [i64p, u8p, ctypes.c_int64, i64p,
                                            ctypes.c_int64, i64p, ctypes.c_int64,
                                            i64p, i64p]
    lib.probe_fill.restype = None
    lib.probe_fill.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p, i64p, i64p,
                               i64p, i64p]
    lib.bucket_build.restype = ctypes.c_int64
    lib.bucket_build.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p, i64p]
    lib.bool_mask_indices.restype = ctypes.c_int64
    lib.bool_mask_indices.argtypes = [u8p, u8p, ctypes.c_int64, ctypes.c_int64, i64p]
    lib.probe_unique_pair.restype = ctypes.c_int64
    lib.probe_unique_pair.argtypes = [i64p, u8p, ctypes.c_int64, i64p,
                                      ctypes.c_int64, i64p, i64p, i64p]
    lib.probe_unique_dense.restype = ctypes.c_int64
    lib.probe_unique_dense.argtypes = [i64p, u8p, ctypes.c_int64, ctypes.c_int64,
                                       ctypes.c_int64, i64p, i64p, i64p, i64p]
    lib.probe_lookup_count_hash.restype = ctypes.c_int64
    lib.probe_lookup_count_hash.argtypes = [i64p, u8p, ctypes.c_int64, i64p, i64p,
                                            ctypes.c_int64, i64p, ctypes.c_int64,
                                            i64p, i64p]
    lib.probe_lookup_count_dense.restype = ctypes.c_int64
    lib.probe_lookup_count_dense.argtypes = [i64p, u8p, ctypes.c_int64,
                                             ctypes.c_int64, ctypes.c_int64, i64p,
                                             ctypes.c_int64, i64p, i64p]
    lib.bucket_scatter.restype = None
    lib.bucket_scatter.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p, i64p]
    _LIB = lib
    return _LIB


def _p(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def native_factorize(keys: np.ndarray) -> Optional[tuple]:
    """(codes, num_groups) in first-occurrence order, or None if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    out = np.empty(len(keys), dtype=np.int64)
    g = lib.factorize_i64(_p(keys, ctypes.c_int64), len(keys), _p(out, ctypes.c_int64))
    return out, int(g)


def native_combine_factorize(a: np.ndarray, b: np.ndarray, b_domain: int) -> Optional[tuple]:
    lib = get_lib()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, dtype=np.int64)
    b = np.ascontiguousarray(b, dtype=np.int64)
    out = np.empty(len(a), dtype=np.int64)
    g = lib.combine_factorize_i64(_p(a, ctypes.c_int64), _p(b, ctypes.c_int64),
                                  len(a), int(b_domain), _p(out, ctypes.c_int64))
    return out, int(g)


def native_join_counts(lcodes: np.ndarray, rcodes: np.ndarray, num_codes: int) -> Optional[np.ndarray]:
    """Per-left-row match counts only (semi/anti joins skip pair materialization)."""
    lib = get_lib()
    if lib is None:
        return None
    lcodes = np.ascontiguousarray(lcodes, dtype=np.int64)
    rcodes = np.ascontiguousarray(rcodes, dtype=np.int64)
    nl, nr = len(lcodes), len(rcodes)
    bucket_counts = np.empty(max(num_codes, 1), dtype=np.int64)
    l_match = np.empty(max(nl, 1), dtype=np.int64)
    lib.join_count(_p(lcodes, ctypes.c_int64), nl, _p(rcodes, ctypes.c_int64), nr,
                   num_codes, _p(bucket_counts, ctypes.c_int64), _p(l_match, ctypes.c_int64))
    return l_match[:nl]


def native_join_indices(lcodes: np.ndarray, rcodes: np.ndarray, num_codes: int) -> Optional[tuple]:
    """Inner-match pairs for compact codes: (l_idx, r_idx, l_match_counts)."""
    lib = get_lib()
    if lib is None:
        return None
    lcodes = np.ascontiguousarray(lcodes, dtype=np.int64)
    rcodes = np.ascontiguousarray(rcodes, dtype=np.int64)
    nl, nr = len(lcodes), len(rcodes)
    bucket_counts = np.empty(max(num_codes, 1), dtype=np.int64)
    l_match = np.empty(max(nl, 1), dtype=np.int64)
    total = lib.join_count(_p(lcodes, ctypes.c_int64), nl, _p(rcodes, ctypes.c_int64), nr,
                           num_codes, _p(bucket_counts, ctypes.c_int64), _p(l_match, ctypes.c_int64))
    offsets = np.concatenate([[0], np.cumsum(bucket_counts[:num_codes])[:-1]]).astype(np.int64) \
        if num_codes else np.zeros(1, np.int64)
    bucket_rows = np.empty(max(nr, 1), dtype=np.int64)
    out_l = np.empty(max(total, 1), dtype=np.int64)
    out_r = np.empty(max(total, 1), dtype=np.int64)
    lib.join_fill(_p(lcodes, ctypes.c_int64), nl, _p(rcodes, ctypes.c_int64), nr, num_codes,
                  _p(offsets, ctypes.c_int64), _p(bucket_rows, ctypes.c_int64),
                  _p(out_l, ctypes.c_int64), _p(out_r, ctypes.c_int64))
    return out_l[:total], out_r[:total], l_match[:nl]


def native_grouped_sum(gids: np.ndarray, vals: np.ndarray, valid: np.ndarray,
                       num_groups: int) -> Optional[tuple]:
    """(sums, counts) or None. vals must be float64 or int64."""
    lib = get_lib()
    if lib is None:
        return None
    gids = np.ascontiguousarray(gids, dtype=np.int64)
    valid8 = np.ascontiguousarray(valid, dtype=np.uint8)
    if vals.dtype == np.float64:
        vals = np.ascontiguousarray(vals)
        out = np.empty(num_groups, dtype=np.float64)
        cnt = np.empty(num_groups, dtype=np.int64)
        lib.grouped_sum_f64(_p(gids, ctypes.c_int64), _p(vals, ctypes.c_double),
                            _p(valid8, ctypes.c_uint8), len(gids), num_groups,
                            _p(out, ctypes.c_double), _p(cnt, ctypes.c_int64))
        return out, cnt
    if vals.dtype == np.int64:
        vals = np.ascontiguousarray(vals)
        out = np.empty(num_groups, dtype=np.int64)
        cnt = np.empty(num_groups, dtype=np.int64)
        lib.grouped_sum_i64(_p(gids, ctypes.c_int64), _p(vals, ctypes.c_int64),
                            _p(valid8, ctypes.c_uint8), len(gids), num_groups,
                            _p(out, ctypes.c_int64), _p(cnt, ctypes.c_int64))
        return out, cnt
    return None


def native_grouped_minmax(gids: np.ndarray, vals: np.ndarray, valid: np.ndarray,
                          num_groups: int) -> Optional[tuple]:
    lib = get_lib()
    if lib is None:
        return None
    gids = np.ascontiguousarray(gids, dtype=np.int64)
    valid8 = np.ascontiguousarray(valid, dtype=np.uint8)
    if vals.dtype == np.float64:
        vals = np.ascontiguousarray(vals)
        mn = np.empty(num_groups, dtype=np.float64)
        mx = np.empty(num_groups, dtype=np.float64)
        lib.grouped_minmax_f64(_p(gids, ctypes.c_int64), _p(vals, ctypes.c_double),
                               _p(valid8, ctypes.c_uint8), len(gids), num_groups,
                               _p(mn, ctypes.c_double), _p(mx, ctypes.c_double))
        return mn, mx
    if vals.dtype == np.int64:
        vals = np.ascontiguousarray(vals)
        mn = np.empty(num_groups, dtype=np.int64)
        mx = np.empty(num_groups, dtype=np.int64)
        lib.grouped_minmax_i64(_p(gids, ctypes.c_int64), _p(vals, ctypes.c_int64),
                               _p(valid8, ctypes.c_uint8), len(gids), num_groups,
                               _p(mn, ctypes.c_int64), _p(mx, ctypes.c_int64))
        return mn, mx
    return None


def native_probe(lcodes: np.ndarray, num_codes: int, bucket_offsets: np.ndarray,
                 bucket_counts: np.ndarray, bucket_rows: np.ndarray) -> Optional[tuple]:
    """Probe prebuilt join buckets: (l_idx, r_idx, l_match_counts) or None.
    Buckets are built once by kernels/join.py ProbeTable; this is the per-morsel
    lookup (all inputs read-only -> safe from concurrent pool threads)."""
    lib = get_lib()
    if lib is None:
        return None
    lcodes = np.ascontiguousarray(lcodes, dtype=np.int64)
    nl = len(lcodes)
    l_match = np.empty(max(nl, 1), dtype=np.int64)
    total = lib.probe_count(_p(lcodes, ctypes.c_int64), nl, int(num_codes),
                            _p(bucket_counts, ctypes.c_int64), _p(l_match, ctypes.c_int64))
    out_l = np.empty(max(total, 1), dtype=np.int64)
    out_r = np.empty(max(total, 1), dtype=np.int64)
    lib.probe_fill(_p(lcodes, ctypes.c_int64), nl, int(num_codes),
                   _p(bucket_offsets, ctypes.c_int64), _p(bucket_counts, ctypes.c_int64),
                   _p(bucket_rows, ctypes.c_int64), _p(out_l, ctypes.c_int64),
                   _p(out_r, ctypes.c_int64))
    return out_l[:total], out_r[:total], l_match[:nl]


def native_i64_map_build(keys: np.ndarray) -> Optional[tuple]:
    """Open-addressing hash map over unique int64 keys -> their positions, in
    an interleaved (key, val) pair layout so a probe touches ONE cache line.
    Returns (slots, cap) or None. Read-only after build, so lookups are safe
    from concurrent pool threads."""
    lib = get_lib()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    n = len(keys)
    cap = 1
    while cap < max(2 * n, 16):
        cap <<= 1
    slots = np.empty(2 * cap, dtype=np.int64)
    slots[1::2] = -1
    lib.i64_pairmap_build(_p(keys, ctypes.c_int64), n, cap, _p(slots, ctypes.c_int64))
    return slots, cap


def native_i64_map_lookup(slots: np.ndarray, cap: int,
                          vals: np.ndarray) -> Optional[np.ndarray]:
    """Positions of vals in the map's key set (-1 for absent), or None."""
    lib = get_lib()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = np.empty(max(len(vals), 1), dtype=np.int64)
    lib.i64_pairmap_lookup(_p(slots, ctypes.c_int64), int(cap),
                           _p(vals, ctypes.c_int64), len(vals),
                           _p(out, ctypes.c_int64))
    return out[:len(vals)]


def native_bucket_build(codes: np.ndarray, num_codes: int) -> Optional[tuple]:
    """(counts, offsets, max_count) per joint code in one C pass — the
    ProbeTable build side of native_probe. codes < 0 are skipped.
    max_count == 1 signals unique build keys (direct-lookup joins legal).
    None if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    codes = np.ascontiguousarray(codes, dtype=np.int64)
    g = max(int(num_codes), 1)
    counts = np.empty(g, dtype=np.int64)
    offsets = np.empty(g, dtype=np.int64)
    mx = lib.bucket_build(_p(codes, ctypes.c_int64), len(codes), g,
                          _p(counts, ctypes.c_int64), _p(offsets, ctypes.c_int64))
    return counts[:num_codes] if num_codes else counts[:0], \
        offsets[:num_codes] if num_codes else offsets[:0], int(mx)


def native_bucket_scatter(codes: np.ndarray, num_codes: int,
                          offsets: np.ndarray, total: int) -> Optional[np.ndarray]:
    """Stable counting-sort scatter of row ids into buckets (row order preserved
    within a bucket), or None. O(n + num_codes), replaces np.argsort."""
    lib = get_lib()
    if lib is None:
        return None
    codes = np.ascontiguousarray(codes, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    rows = np.empty(max(int(total), 1), dtype=np.int64)
    lib.bucket_scatter(_p(codes, ctypes.c_int64), len(codes), max(int(num_codes), 1),
                       _p(offsets, ctypes.c_int64), _p(rows, ctypes.c_int64))
    return rows[:total]


def native_probe_lookup_count(vals: np.ndarray, valid: Optional[np.ndarray],
                              lookup, bucket_counts: np.ndarray,
                              num_codes: int) -> Optional[tuple]:
    """Fused single-i64-key probe: value -> build joint code -> match count in
    one C pass. lookup is ProbeTable's ("dense", lo, hi) or ("hashmap", hm)
    descriptor. Returns (codes, l_match_counts, total) or None."""
    lib = get_lib()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    n = len(vals)
    vp = None
    if valid is not None:
        valid = np.ascontiguousarray(valid, dtype=np.uint8)
        vp = _p(valid, ctypes.c_uint8)
    codes = np.empty(max(n, 1), dtype=np.int64)
    l_match = np.empty(max(n, 1), dtype=np.int64)
    if lookup[0] == "dense":
        total = lib.probe_lookup_count_dense(
            _p(vals, ctypes.c_int64), vp, n, int(lookup[1]), int(lookup[2]),
            _p(bucket_counts, ctypes.c_int64), int(num_codes),
            _p(codes, ctypes.c_int64), _p(l_match, ctypes.c_int64))
    else:
        slots, cap = lookup[1]
        total = lib.probe_lookup_count_pair(
            _p(vals, ctypes.c_int64), vp, n, _p(slots, ctypes.c_int64), int(cap),
            _p(bucket_counts, ctypes.c_int64), int(num_codes),
            _p(codes, ctypes.c_int64), _p(l_match, ctypes.c_int64))
    return codes[:n], l_match[:n], int(total)


def native_probe_fill(codes: np.ndarray, num_codes: int, bucket_offsets: np.ndarray,
                      bucket_counts: np.ndarray, bucket_rows: np.ndarray,
                      total: int) -> Optional[tuple]:
    """probe_fill only (match total already known from the fused count pass)."""
    lib = get_lib()
    if lib is None:
        return None
    codes = np.ascontiguousarray(codes, dtype=np.int64)
    out_l = np.empty(max(total, 1), dtype=np.int64)
    out_r = np.empty(max(total, 1), dtype=np.int64)
    lib.probe_fill(_p(codes, ctypes.c_int64), len(codes), int(num_codes),
                   _p(bucket_offsets, ctypes.c_int64), _p(bucket_counts, ctypes.c_int64),
                   _p(bucket_rows, ctypes.c_int64), _p(out_l, ctypes.c_int64),
                   _p(out_r, ctypes.c_int64))
    return out_l[:total], out_r[:total]


def native_probe_unique(vals: np.ndarray, valid: Optional[np.ndarray],
                        direct) -> Optional[tuple]:
    """Unique-build-key probe: one random access per row. `direct` is
    ("pairmap", slots, cap) over value -> build row, or
    ("dense", lo, hi, row_of_code). Returns (ridx_full, matched_l, matched_r)
    or None if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    n = len(vals)
    vp = None
    if valid is not None:
        valid = np.ascontiguousarray(valid, dtype=np.uint8)
        vp = _p(valid, ctypes.c_uint8)
    ridx_full = np.empty(max(n, 1), dtype=np.int64)
    out_l = np.empty(max(n, 1), dtype=np.int64)
    out_r = np.empty(max(n, 1), dtype=np.int64)
    if direct[0] == "pairmap":
        m = lib.probe_unique_pair(_p(vals, ctypes.c_int64), vp, n,
                                  _p(direct[1], ctypes.c_int64), int(direct[2]),
                                  _p(ridx_full, ctypes.c_int64),
                                  _p(out_l, ctypes.c_int64), _p(out_r, ctypes.c_int64))
    else:
        m = lib.probe_unique_dense(_p(vals, ctypes.c_int64), vp, n,
                                   int(direct[1]), int(direct[2]),
                                   _p(direct[3], ctypes.c_int64),
                                   _p(ridx_full, ctypes.c_int64),
                                   _p(out_l, ctypes.c_int64), _p(out_r, ctypes.c_int64))
    return ridx_full[:n], out_l[:m], out_r[:m]


def native_mask_indices(arr) -> Optional[np.ndarray]:
    """Selection vector (int64 row indices) of a pyarrow BooleanArray in one
    word-wise C pass over the bitmaps; nulls drop. None if lib unavailable or
    the array isn't a plain boolean array."""
    import pyarrow as pa

    lib = get_lib()
    if lib is None:
        return None
    if isinstance(arr, pa.ChunkedArray):
        if arr.num_chunks == 1:
            arr = arr.chunk(0)
        else:
            arr = arr.combine_chunks()
    if not isinstance(arr, pa.BooleanArray):
        return None
    bufs = arr.buffers()
    if len(bufs) != 2 or bufs[1] is None:
        return None
    bits = ctypes.cast(bufs[1].address, ctypes.POINTER(ctypes.c_uint8))
    validity = ctypes.cast(bufs[0].address, ctypes.POINTER(ctypes.c_uint8)) \
        if bufs[0] is not None else None
    out = np.empty(max(len(arr), 1), dtype=np.int64)
    m = lib.bool_mask_indices(bits, validity, arr.offset, len(arr),
                              _p(out, ctypes.c_int64))
    return out[:m]
