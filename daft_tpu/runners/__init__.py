"""Runner registry (reference: daft/runners/runner.py:26 Runner ABC + get_or_create_runner)."""

from __future__ import annotations

import os
from typing import Optional

from .native import NativeRunner, Runner

_RUNNER: Optional[Runner] = None


def get_or_create_runner() -> Runner:
    global _RUNNER
    if _RUNNER is None:
        name = os.environ.get("DAFT_TPU_RUNNER", "native").lower()
        if name == "native":
            _RUNNER = NativeRunner()
        else:
            raise ValueError(f"unknown runner {name!r}")
    return _RUNNER


def set_runner(runner: Runner) -> None:
    global _RUNNER
    _RUNNER = runner


__all__ = ["Runner", "NativeRunner", "get_or_create_runner", "set_runner"]
