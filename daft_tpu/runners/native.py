"""Single-host runner: optimize → translate → stream execute.

Reference parity: daft/runners/native_runner.py:64 (NativeRunner.run/run_iter).
"""

from __future__ import annotations

from typing import Iterator, List

from ..core.micropartition import MicroPartition
from ..plan.builder import LogicalPlanBuilder


class Runner:
    def run(self, builder: LogicalPlanBuilder) -> List[MicroPartition]:
        return list(self.run_iter(builder))

    def run_iter(self, builder: LogicalPlanBuilder) -> Iterator[MicroPartition]:
        raise NotImplementedError


class NativeRunner(Runner):
    def run_iter(self, builder: LogicalPlanBuilder) -> Iterator[MicroPartition]:
        import time
        import uuid

        from ..execution.executor import execute_plan
        from ..observability import (QueryEnd, QueryOptimized, QueryStart,
                                     flight, notify, subscribers_active)
        from ..observability.runtime_stats import StatsCollector, set_collector
        from ..plan.physical import translate

        observed = subscribers_active()
        # the flight recorder records EVERY query (bounded ring, anomaly
        # triggers), not just subscriber-observed ones; None when disabled
        frec = flight.recorder()
        qid = uuid.uuid4().hex[:12] if (observed or frec is not None) else ""
        t_start = time.perf_counter()
        reg_before = {}
        if observed or frec is not None:
            from ..observability.metrics import registry

            # per-query engine-path attribution (device batches, shuffle
            # bytes): counter deltas land in QueryEnd.metrics and the
            # flight ring's query record
            reg_before = registry().snapshot()
        if observed:
            notify("on_query_start", QueryStart(qid, builder.plan.display()))
        t0 = time.perf_counter()
        optimized = builder.optimize()
        phys = translate(optimized.plan)
        fkey = flight.plan_key(phys.display()) if frec is not None else ""
        if observed:
            notify("on_query_optimized", QueryOptimized(
                qid, optimized.plan.display(), phys.display(),
                time.perf_counter() - t0))
        from ..observability import placement
        from ..observability.runtime_stats import current_collector

        # inherit any ambient collector (explain_analyze routes through the
        # runner — it wins even with subscribers attached, who then see the
        # same collector's stats); save/restore around every pull so
        # interleaved queries on one thread never clobber each other's stats
        prev = current_collector()
        collector = prev if prev is not None \
            else (StatsCollector() if observed else None)
        # placement scope, same inheritance/save-restore discipline: an
        # ambient scope (explain_placement) wins; otherwise an observed query
        # gets its own so QueryEnd carries the decisions; unobserved queries
        # run scope-less (the zero-overhead path)
        prev_scope = placement.current_scope()
        pscope = prev_scope if prev_scope is not None \
            else (placement.PlacementScope() if observed else None)
        rows = 0
        err: str = None
        try:
            set_collector(collector)
            placement.set_scope(pscope)
            try:
                stream = execute_plan(phys)
            finally:
                set_collector(prev)
                placement.set_scope(prev_scope)
            while True:
                set_collector(collector)
                placement.set_scope(pscope)
                try:
                    part = next(stream)
                except StopIteration:
                    break
                finally:
                    set_collector(prev)
                    placement.set_scope(prev_scope)
                rows += part.num_rows
                yield part
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            set_collector(prev)
            placement.set_scope(prev_scope)
            seconds = time.perf_counter() - t_start
            deltas = {}
            if observed or frec is not None:
                from ..observability.metrics import registry

                deltas = registry().diff(reg_before)
            placements = pscope.to_dicts() if pscope is not None else []
            if observed:
                stats = collector.finish() if collector else []
                for s in stats:
                    notify("on_operator_stats", qid, s)
                notify("on_query_end", QueryEnd(
                    qid, rows, seconds, err, stats,
                    metrics=deltas, placements=placements))
            if frec is not None:
                # always-on black box: the query record + the slow-query /
                # query-error anomaly checks (observability/flight.py)
                frec.note_query(fkey, seconds, query_id=qid, rows=rows,
                                error=err, metrics=deltas,
                                placements=placements or None)
