"""Single-host runner: optimize → translate → stream execute.

Reference parity: daft/runners/native_runner.py:64 (NativeRunner.run/run_iter).
"""

from __future__ import annotations

from typing import Iterator, List

from ..core.micropartition import MicroPartition
from ..plan.builder import LogicalPlanBuilder


class Runner:
    def run(self, builder: LogicalPlanBuilder) -> List[MicroPartition]:
        return list(self.run_iter(builder))

    def run_iter(self, builder: LogicalPlanBuilder) -> Iterator[MicroPartition]:
        raise NotImplementedError


class NativeRunner(Runner):
    def run_iter(self, builder: LogicalPlanBuilder) -> Iterator[MicroPartition]:
        from ..execution.executor import execute_plan
        from ..plan.physical import translate

        optimized = builder.optimize()
        phys = translate(optimized.plan)
        yield from execute_plan(phys)
